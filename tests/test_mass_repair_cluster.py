"""Chaos: dead-node mass repair at cluster scale (ISSUE 11).

Test 1 — a node holding shards of 33 EC volumes is killed while clients
hammer reads: the master detects the death, the orchestrator ranks the
batch by exposure (a 4-shard-loss volume is in the same batch), spreads
rebuild targets, and every volume is rebuilt byte-identically within the
configured repair budget with ZERO client 5xx.

Test 2 — the master is SIGKILLed while mass-repair jobs are journaled
running (held open by a delay fault on the batch serve path): the
restarted master replays the journal and completes the batch
exactly-once — every shard held by exactly one node, no duplicates.

Setup note: EC files are generated with small test block sizes (the
mounted EcVolume's block-size attributes are overridden to match) so 33
volumes stay a few-KB each instead of the 1MB-padded default shards;
the batch protocol and orchestrator under test never consult block
sizes.  The default-size path is covered by test_ec_partial's chaos.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.stats.metrics import (
    EC_PARTIAL_BYTES,
    REPAIR_BATCH_BYTES,
    REPAIR_BATCH_VOLUMES,
)
from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec.encoder import (
    generate_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.util import faultpoint

from helpers import free_port, make_volume, start_master_cluster

LARGE = 10000
SMALL = 100
N_SRV = 5


def _stage_volumes(tmp_path, servers, n_volumes, victim_sids):
    """Encode n_volumes tiny EC volumes and mount their shards across
    `servers`; the victim (servers[0]) holds `victim_sids(v)` of each.
    Returns {vid: {fid: payload}}."""
    stage = tmp_path / "stage"
    stage.mkdir()
    needles: dict = {}
    for v in range(1, n_volumes + 1):
        d = stage / str(v)
        d.mkdir()
        vol = make_volume(str(d), volume_id=v, n_needles=10, seed=v,
                          max_size=2000)
        needles[v] = {}
        for i in range(1, 11):
            n = vol.read_needle(i)
            needles[v][f"{v},{i:x}{n.cookie:08x}"] = bytes(n.data)
        base = vol.file_name()
        vol.close()
        generate_ec_files(base, large_block_size=LARGE,
                          small_block_size=SMALL, codec_name="cpu",
                          slice_size=1 << 20)
        write_sorted_file_from_idx(base)
        vic = set(victim_sids(v))
        assign = {j: [] for j in range(len(servers))}
        assign[0] = sorted(vic)
        rest = [sid for sid in range(ecc.TOTAL_SHARDS) if sid not in vic]
        for k, sid in enumerate(rest):
            assign[1 + k % (len(servers) - 1)].append(sid)
        for j, sids in assign.items():
            if not sids:
                continue
            tbase = servers[j].store.locations[0].base_name(v, "")
            shutil.copy(base + ".ecx", tbase + ".ecx")
            for sid in sids:
                shutil.copy(base + ecc.to_ext(sid), tbase + ecc.to_ext(sid))
            servers[j].store.mount_ec_shards(v, "", sids)
            ev = servers[j].store.find_ec_volume(v)
            ev.large_block_size = LARGE
            ev.small_block_size = SMALL
    return needles


def _start_servers(tmp_path, master_grpc, n=N_SRV):
    from seaweedfs_tpu.volume.server import VolumeServer

    addrs = ([master_grpc] if isinstance(master_grpc, str)
             else list(master_grpc))
    servers = []
    for i in range(n):
        d = tmp_path / f"vol{i}"
        d.mkdir()
        s = VolumeServer(
            directories=[str(d)], master_addresses=addrs,
            ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
            rack=f"rack{i % 2}", data_center="dc1", max_volume_count=600)
        s.start()
        servers.append(s)
    return servers


@pytest.mark.chaos
def test_chaos_dead_node_mass_repair_under_reads(tmp_path):
    """Kill a node holding shards of 33 EC volumes under concurrent
    client reads: detection -> exposure-ranked plan -> spread batched
    rebuild, zero 5xx, byte identity, inside the configured bound."""
    from seaweedfs_tpu.master.server import MasterServer

    deadline_s = 90.0
    jd = tmp_path / "journal"
    jd.mkdir()
    master, cluster = start_master_cluster(
        str(jd), volume_size_limit_mb=64, pulse_seconds=0.5,
        lifecycle_dir=str(jd), repair_deadline_s=deadline_s)
    servers = []
    try:
        servers = _start_servers(
            tmp_path, [f"127.0.0.1:{m.grpc_port}" for m in cluster])
        deadline = time.time() + 20
        while time.time() < deadline and len(master.topo.nodes) < N_SRV:
            time.sleep(0.1)
        assert len(master.topo.nodes) == N_SRV

        # victim holds 2 shards of most volumes, 4 of volume 1 — volume
        # 1 lands at the decode floor and must plan in exposure class 0
        V = 33
        needles = _stage_volumes(
            tmp_path, servers, V,
            victim_sids=lambda v: (
                [0, 1, 2, 3] if v == 1
                else [v % 14, (v + 1) % 14]))
        deadline = time.time() + 30
        while time.time() < deadline and any(
                len(master.topo.lookup_ec_shards(v)) < 14
                for v in range(1, V + 1)):
            time.sleep(0.2)
        assert all(len(master.topo.lookup_ec_shards(v)) == 14
                   for v in range(1, V + 1))

        reader = servers[1]

        def check_reads() -> int:
            bad = 0
            for v in (1, 5, 17, 30):
                for fid, want in list(needles[v].items())[:3]:
                    try:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{reader.port}/{fid}",
                                timeout=15) as r:
                            assert r.read() == want, f"corrupt {fid}"
                    except urllib.error.HTTPError as e:
                        if e.code >= 500:
                            bad += 1
                    except OSError:
                        bad += 1
            return bad

        assert check_reads() == 0

        before_bytes = REPAIR_BATCH_BYTES.labels().value
        before_floor = REPAIR_BATCH_VOLUMES.labels("0").value
        before_recv = EC_PARTIAL_BYTES.labels("recv").value
        victim = servers[0]
        victim.stop()
        t_kill = time.time()

        errs: list = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                errs.append(check_reads())
                time.sleep(0.1)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()

        def all_healed():
            return all(len(master.topo.lookup_ec_shards(v)) == 14
                       for v in range(1, V + 1))

        try:
            deadline = time.time() + 30
            while (time.time() < deadline
                   and f"127.0.0.1:{victim.port}" in master.topo.nodes):
                time.sleep(0.2)
            assert f"127.0.0.1:{victim.port}" not in master.topo.nodes, \
                "death never detected"
            deadline = time.time() + deadline_s
            while time.time() < deadline and not all_healed():
                time.sleep(0.3)
        finally:
            stop.set()
            t.join(timeout=15)
        elapsed = time.time() - t_kill
        assert all_healed(), {
            v: len(master.topo.lookup_ec_shards(v))
            for v in range(1, V + 1)
            if len(master.topo.lookup_ec_shards(v)) < 14}
        assert elapsed < deadline_s, f"repair blew the bound: {elapsed}"
        assert sum(errs) == 0, f"client 5xx during mass repair: {sum(errs)}"
        assert check_reads() == 0

        st = master.mass_repair.status()
        assert st["counts"]["deaths"] >= 1
        assert st["counts"]["repaired"] >= V
        # the floor volume was classed exposure-0 and repaired
        assert REPAIR_BATCH_VOLUMES.labels("0").value > before_floor
        assert master.lifecycle.journal.get("1:mass_repair")["state"] == \
            "done"
        assert REPAIR_BATCH_BYTES.labels().value > before_bytes
        # the batch rode the aggregated partial transport
        assert EC_PARTIAL_BYTES.labels("recv").value > before_recv
        # no shard duplicated by the repair
        for v in range(1, V + 1):
            for sid, nodes in master.topo.lookup_ec_shards(v).items():
                assert len(nodes) == 1, (v, sid, [n.id for n in nodes])
    finally:
        for s in servers[1:]:
            s.stop()
        for m in cluster:
            m.stop()


# ---------------------------------------------------------------------------
# chaos: SIGKILL the master mid-batch, journal resumes exactly-once
# ---------------------------------------------------------------------------


def _spawn_master(mport, jd, extra_env=None):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "master",
         "-port", str(mport),
         "-volumeSizeLimitMB", "64",
         "-lifecycleDir", jd],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)


def _journal_jobs(jd) -> dict:
    jobs: dict = {}
    try:
        with open(os.path.join(jd, "lifecycle.journal.jsonl")) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "key" in rec:
                    jobs[rec["key"]] = rec
    except FileNotFoundError:
        pass
    return jobs


@pytest.mark.chaos
def test_chaos_master_sigkill_mid_batch_resumes(tmp_path):
    """SIGKILL the master while mass-repair jobs are journaled RUNNING
    (a delay fault on repair.batch.source holds the batch open): the
    restarted master replays them as pending, the batch completes, and
    every shard ends on exactly one node."""
    jd = str(tmp_path / "journal")
    os.makedirs(jd)
    mport = free_port()
    master_proc = _spawn_master(mport, jd)
    servers = []
    second = None
    V = 6
    try:
        servers = _start_servers(tmp_path, f"127.0.0.1:{mport + 10000}")
        # wait for the subprocess master to register everyone
        deadline = time.time() + 90
        up = False
        while time.time() < deadline and not up:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/cluster/status",
                        timeout=5) as r:
                    doc = json.loads(r.read())
                    up = len(doc.get("Topology", {}).get(
                        "DataNodes", doc.get("DataNodes", []))) >= N_SRV
            except OSError:
                time.sleep(0.5)
                continue
            if not up:
                time.sleep(0.5)
        assert up, "master subprocess never registered the volume servers"

        needles = _stage_volumes(
            tmp_path, servers, V,
            victim_sids=lambda v: [v % 14, (v + 1) % 14])

        def lookup_shards(v):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{mport}/dir/lookup?volumeId={v}",
                        timeout=5) as r:
                    return len(json.loads(r.read()).get("locations", []))
            except (OSError, ValueError):
                return 0

        deadline = time.time() + 30
        while time.time() < deadline and any(
                lookup_shards(v) == 0 for v in range(1, V + 1)):
            time.sleep(0.3)

        # every batch-served partial job stalls 1.5s: the SIGKILL window
        # (the fault lives in THIS process — the volume servers)
        faultpoint.set_fault("repair.batch.source", "delay", delay=1.5)
        servers[0].stop()

        deadline = time.time() + 60
        killed = False
        while time.time() < deadline:
            jobs = _journal_jobs(jd)
            running = [k for k, j in jobs.items()
                       if j.get("transition") == "mass_repair"
                       and j.get("state") == "running"]
            if running:
                master_proc.kill()
                master_proc.wait(timeout=10)
                killed = True
                break
            time.sleep(0.05)
        assert killed, f"no mass_repair job reached running: " \
                       f"{_journal_jobs(jd)}"
        faultpoint.clear_fault("repair.batch.source")

        second = _spawn_master(mport, jd)

        def all_mounted():
            """Exactly one holder per shard across the survivors."""
            for v in range(1, V + 1):
                held: dict = {}
                for s in servers[1:]:
                    for sid in s.store.status()["ec_volumes"].get(v, []):
                        held[sid] = held.get(sid, 0) + 1
                if sorted(held) != list(range(14)):
                    return False
                if any(c != 1 for c in held.values()):
                    pytest.fail(f"duplicate shard holders: vol {v} {held}")
            return True

        deadline = time.time() + 120
        while time.time() < deadline and not all_mounted():
            time.sleep(0.5)
        assert all_mounted(), {
            v: sorted({sid for s in servers[1:]
                       for sid in s.store.status()["ec_volumes"]
                       .get(v, [])})
            for v in range(1, V + 1)}

        jobs = _journal_jobs(jd)
        mass = {k: j for k, j in jobs.items()
                if j.get("transition") == "mass_repair"}
        assert len(mass) == V, sorted(mass)
        assert all(j["state"] == "done" for j in mass.values()), mass
        assert any(j.get("resumed") for j in mass.values()), \
            "no job carries the journal-resume marker"

        # byte identity through the healed cluster
        reader = servers[1]
        for v in (1, V):
            for fid, want in list(needles[v].items())[:4]:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{reader.port}/{fid}",
                        timeout=15) as r:
                    assert r.read() == want, f"corrupt read {fid}"
    finally:
        faultpoint.clear_fault("repair.batch.source")
        for s in servers[1:]:
            s.stop()
        for p in (master_proc, second):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
