"""Lifecycle plane units (ISSUE 9): policy parsing, the crash-safe job
journal, controller planning against fake topology state, TTL expiry
wiring, and the pure balance-move planners the shell and the controller
share.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from helpers import free_port

from seaweedfs_tpu.maintenance import JobJournal, PolicySet
from seaweedfs_tpu.maintenance.journal import job_key
from seaweedfs_tpu.pb import master_pb2
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.topology.topology import DataNode, VolumeInfo
from seaweedfs_tpu.util import faultpoint


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


def test_policy_defaults():
    p = PolicySet()
    pol = p.for_collection("anything")
    assert pol.seal_full_percent == 95.0
    assert pol.ec_cooldown_seconds < 0  # EC disabled by default
    assert pol.tier_backend == ""
    assert pol.vacuum_garbage_ratio == 0.3
    assert pol.ttl_expire


def test_policy_per_collection_override():
    p = PolicySet.parse({
        "*": {"seal_full_percent": 80},
        "photos": {"ec_cooldown_seconds": 10, "tier_backend": "s3.cold"},
    })
    assert p.for_collection("photos").ec_cooldown_seconds == 10
    assert p.for_collection("photos").tier_backend == "s3.cold"
    # photos does NOT inherit the '*' seal override (whole-policy wins)
    assert p.for_collection("other").seal_full_percent == 80


def test_policy_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown lifecycle policy"):
        PolicySet.parse({"*": {"not_a_field": 1}})
    with pytest.raises(ValueError):
        PolicySet.parse({"*": "not an object"})


def test_policy_parse_string_and_roundtrip():
    p = PolicySet.parse('{"*": {"rebalance_skew": 2}}')
    assert p.for_collection("x").rebalance_skew == 2
    again = PolicySet.parse(p.dumps())
    assert again.to_dict() == p.to_dict()


# ---------------------------------------------------------------------------
# TTL expiry helper (satellite: ttl.py wired into the lifecycle)
# ---------------------------------------------------------------------------


def test_ttl_seconds_and_expired():
    t = TTL.parse("3m")
    assert t.seconds() == 180
    now = time.time()
    assert t.expired(now - 181, now=now)
    assert not t.expired(now - 60, now=now)
    # empty TTL never expires, nor does an unknown modified time
    assert not TTL().expired(now - 10**9, now=now)
    assert not t.expired(0, now=now)


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def _mk_job(vid, transition, state="pending", **extra):
    return {"key": job_key(vid, transition), "volume_id": vid,
            "transition": transition, "state": state,
            "created_ms": int(time.time() * 1000), "attempts": 0, **extra}


def test_journal_roundtrip_and_replay(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = JobJournal(path)
    j.put(_mk_job(1, "seal"))
    j.put(_mk_job(2, "ec_encode"))
    j.update(job_key(1, "seal"), state="done")
    j.update(job_key(2, "ec_encode"), state="running")

    j2 = JobJournal(path)
    assert j2.get(job_key(1, "seal"))["state"] == "done"
    # running replays as pending (idempotent RPCs, safe to re-run) and
    # is flagged resumed
    rec = j2.get(job_key(2, "ec_encode"))
    assert rec["state"] == "pending"
    assert rec["resumed"] == 1
    assert len(j2.active()) == 1


def test_journal_memory_only_mode():
    j = JobJournal(None)
    j.put(_mk_job(1, "vacuum"))
    assert j.get(job_key(1, "vacuum"))["state"] == "pending"
    assert j.counts() == {"pending": 1}


def test_journal_survives_torn_tail(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = JobJournal(path)
    j.put(_mk_job(1, "seal"))
    with open(path, "a") as f:
        f.write('{"key": "2:seal", "state": "pe')  # torn write, no \n
    j2 = JobJournal(path)
    assert j2.get(job_key(1, "seal")) is not None
    assert j2.get(job_key(2, "seal")) is None


def test_journal_compaction_bounds_file(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = JobJournal(path)
    j.COMPACT_SLACK = 8
    j.put(_mk_job(1, "vacuum"))
    for i in range(40):
        j.update(job_key(1, "vacuum"),
                 state="done" if i % 2 else "pending")
    with open(path) as f:
        lines = [ln for ln in f if ln.strip()]
    assert len(lines) <= 10  # compacted to ~live keys, not 41 lines
    assert JobJournal(path).get(job_key(1, "vacuum")) is not None


def test_journal_write_fault_fails_loud(tmp_path):
    j = JobJournal(str(tmp_path / "j.jsonl"))
    faultpoint.set_fault("lifecycle.journal.write", "error", count=1)
    try:
        with pytest.raises(Exception):
            j.put(_mk_job(1, "seal"))
        # the failed put must not half-register the job
        assert j.get(job_key(1, "seal")) is None
    finally:
        faultpoint.clear_fault("all")
    j.put(_mk_job(1, "seal"))  # works once the fault is gone
    assert j.get(job_key(1, "seal"))["state"] == "pending"


# ---------------------------------------------------------------------------
# controller planning (fake topology, no sockets)
# ---------------------------------------------------------------------------


def _mk_master(tmp_path=None, policy=None, **kw):
    from seaweedfs_tpu.master.server import MasterServer

    return MasterServer(
        ip="127.0.0.1", port=free_port(), volume_size_limit_mb=1,
        lifecycle_dir=str(tmp_path) if tmp_path else "",
        lifecycle_policy=policy, **kw)


def _add_node(master, nid, volumes, ec_vids=()):
    n = DataNode(id=nid, public_url=nid,
                 grpc_address=f"{nid.rsplit(':', 1)[0]}:"
                              f"{int(nid.rsplit(':', 1)[1]) + 10000}")
    n.volumes = volumes
    n.ec_shards = {vid: 0x3FFF for vid in ec_vids}
    master.topo.nodes[nid] = n
    return n


def test_evaluate_seal_vacuum_ttl(tmp_path):
    m = _mk_master(tmp_path)
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        1: VolumeInfo(1, size=1 << 20, modified_at_second=now - 100),
        2: VolumeInfo(2, size=500_000, deleted_byte_count=250_000,
                      modified_at_second=now - 10),
        3: VolumeInfo(3, size=1000, ttl=TTL.parse("1m").to_uint32(),
                      modified_at_second=now - 7200),
        4: VolumeInfo(4, size=10, modified_at_second=now - 5),  # healthy
    })
    plans = {p["key"]: p for p in m.lifecycle.evaluate()}
    assert plans["1:seal"]["transition"] == "seal"
    assert plans["2:vacuum"]["bytes"] == 500_000
    assert "3:ttl_expire" in plans
    assert not any(p["volume_id"] == 4 for p in plans.values())


def test_evaluate_ec_cooldown_gate(tmp_path):
    m = _mk_master(tmp_path, policy={"*": {"ec_cooldown_seconds": 300}})
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        1: VolumeInfo(1, size=1 << 19, read_only=True,
                      modified_at_second=now - 100),   # too fresh
        2: VolumeInfo(2, size=1 << 19, read_only=True,
                      modified_at_second=now - 400),   # cold enough
    })
    keys = {p["key"] for p in m.lifecycle.evaluate()}
    assert "2:ec_encode" in keys
    assert "1:ec_encode" not in keys


def test_evaluate_tier_follows_ec_and_keeps_source(tmp_path):
    m = _mk_master(tmp_path, policy={"*": {
        "ec_cooldown_seconds": 0, "tier_backend": "s3.cold"}})
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        1: VolumeInfo(1, size=1 << 19, read_only=True,
                      modified_at_second=now - 50),
        2: VolumeInfo(2, size=1 << 19, read_only=True,
                      modified_at_second=now - 50),
    }, ec_vids=(2,))
    plans = {p["key"]: p for p in m.lifecycle.evaluate()}
    # v1 not yet encoded -> ec first, and the tier stage pins the source
    assert plans["1:ec_encode"]["keep_source"] is True
    # v2 already encoded -> its .dat tiers now
    assert plans["2:tier"]["backend"] == "s3.cold"


def test_evaluate_half_sealed_volume_replans_seal(tmp_path):
    m = _mk_master(tmp_path)
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001",
              {1: VolumeInfo(1, size=1 << 20, read_only=True,
                             modified_at_second=now - 10)})
    _add_node(m, "127.0.0.1:9002",
              {1: VolumeInfo(1, size=1 << 20, read_only=False,
                             modified_at_second=now - 10)})
    keys = {p["key"] for p in m.lifecycle.evaluate()}
    assert "1:seal" in keys  # sealed means sealed on EVERY replica


def test_submit_dedups_and_serializes_per_volume(tmp_path):
    m = _mk_master(tmp_path)
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        1: VolumeInfo(1, size=1 << 20, deleted_byte_count=900_000,
                      modified_at_second=now - 100),
    })
    plans = m.lifecycle.evaluate()
    accepted = m.lifecycle.submit(plans)
    assert [j["key"] for j in accepted] == ["1:seal"]
    # same plan again: active job suppresses the duplicate; and a
    # second transition for the same volume is serialized behind it
    assert m.lifecycle.submit(plans) == []
    assert m.lifecycle.submit([
        {"key": "1:vacuum", "volume_id": 1, "transition": "vacuum",
         "collection": "", "node": "127.0.0.1:9001", "holders": [],
         "bytes": 10},
    ]) == []


def test_submit_reissue_cooldown_for_vacuum(tmp_path):
    m = _mk_master(tmp_path)
    plan = {"key": "7:vacuum", "volume_id": 7, "transition": "vacuum",
            "collection": "", "node": "n1", "holders": ["n1"],
            "bytes": 10}
    assert m.lifecycle.submit([plan])
    m.lifecycle.journal.update("7:vacuum", state="done")
    # freshly done: suppressed
    assert m.lifecycle.submit([plan]) == []
    # pretend it finished long ago (backdate under the journal lock —
    # put() always re-stamps updated_ms): reissued
    with m.lifecycle.journal._lock:
        m.lifecycle.journal._jobs["7:vacuum"]["updated_ms"] = (
            int(time.time() * 1000) - 10_000_000)
    assert m.lifecycle.submit([plan])


def test_failed_job_resubmit_preserves_attempts_then_parks(tmp_path):
    """A failing transition keeps its attempt counter across
    re-submissions, so MAX_ATTEMPTS really parks it instead of retrying
    forever with a fresh counter."""
    m = _mk_master(tmp_path)
    plan = {"key": "8:seal", "volume_id": 8, "transition": "seal",
            "collection": "", "node": "127.0.0.1:9001",
            "holders": ["127.0.0.1:9001"], "bytes": 0}
    assert m.lifecycle.submit([plan])
    m.lifecycle.journal.update("8:seal", state="failed", attempts=2)
    accepted = m.lifecycle.submit([plan])
    assert accepted and accepted[0]["attempts"] == 2  # preserved
    # no volume server behind 9001: the 3rd attempt fails -> parked
    res = m.lifecycle.run_pending(wait=True)
    assert res and res[0]["state"] == "parked", res
    assert m.lifecycle.journal.get("8:seal")["attempts"] == 3
    # parked jobs are never resubmitted
    assert m.lifecycle.submit([plan]) == []


def test_run_pending_scoped_by_keys(tmp_path):
    m = _mk_master(tmp_path)
    for vid in (31, 32):
        m.lifecycle.submit([
            {"key": f"{vid}:seal", "volume_id": vid,
             "transition": "seal", "collection": "",
             "node": "127.0.0.1:9001", "holders": ["127.0.0.1:9001"],
             "bytes": 0}])
    res = m.lifecycle.run_pending(wait=True, keys={"31:seal"})
    assert [r["key"] for r in res] == ["31:seal"]
    # the unscoped job is untouched
    assert m.lifecycle.journal.get("32:seal")["state"] == "pending"


def test_done_seal_never_reissued(tmp_path):
    m = _mk_master(tmp_path)
    plan = {"key": "9:tier", "volume_id": 9, "transition": "tier",
            "collection": "", "node": "n1", "holders": ["n1"],
            "bytes": 10, "backend": "s3.x"}
    assert m.lifecycle.submit([plan])
    m.lifecycle.journal.update("9:tier", state="done")
    rec = m.lifecycle.journal.get("9:tier")
    rec["updated_ms"] = 0  # even "long ago" done tier stays done
    m.lifecycle.journal.put(rec)
    assert m.lifecycle.submit([plan]) == []


def test_journal_replay_resumes_into_controller(tmp_path):
    m = _mk_master(tmp_path)
    m.lifecycle.submit([
        {"key": "5:ec_encode", "volume_id": 5, "transition": "ec_encode",
         "collection": "", "node": "n1", "holders": ["n1"], "bytes": 10},
    ])
    m.lifecycle.journal.update("5:ec_encode", state="running")
    # new controller over the same dir (a restarted master)
    m2 = _mk_master(tmp_path)
    active = m2.lifecycle.journal.active()
    assert [j["key"] for j in active] == ["5:ec_encode"]
    assert active[0]["state"] == "pending"


def test_status_shape(tmp_path):
    m = _mk_master(tmp_path)
    st = m.lifecycle.status()
    assert st["enabled"] is False
    assert "policies" in st and "*" in st["policies"]
    assert st["journalPath"].endswith("lifecycle.journal.jsonl")


def test_vacuum_plan_carries_policy_ratio(tmp_path):
    """Execution must gate on the POLICY's garbage ratio, not the
    master's global default — otherwise a 0.1 policy against the 0.3
    default plans forever and compacts never."""
    m = _mk_master(tmp_path, policy={"*": {"vacuum_garbage_ratio": 0.1}})
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        2: VolumeInfo(2, size=500_000, deleted_byte_count=100_000,
                      modified_at_second=now - 10),  # 20% garbage
    })
    plans = {p["key"]: p for p in m.lifecycle.evaluate()}
    assert plans["2:vacuum"]["ratio"] == 0.1


def test_master_vacuum_skips_read_only_volumes(tmp_path):
    """Sealed volumes are EC/tier candidates; a vacuum commit racing a
    tier upload would swap the .dat mid-transfer, so read-only volumes
    are exempt from the vacuum sweep (reference behavior)."""
    m = _mk_master(tmp_path)
    now = int(time.time())
    _add_node(m, "127.0.0.1:9001", {
        3: VolumeInfo(3, size=100, deleted_byte_count=90, read_only=True,
                      modified_at_second=now - 10),
    })
    assert m.vacuum_volume(3, threshold=0.1) is False


def test_ttl_expire_with_no_live_holder_fails_not_done(tmp_path):
    """ttl_expire is done-forever once journaled: succeeding vacuously
    while every holder is offline would retain expired data for good."""
    m = _mk_master(tmp_path)
    assert m.lifecycle.submit([
        {"key": "6:ttl_expire", "volume_id": 6,
         "transition": "ttl_expire", "collection": "",
         "node": "127.0.0.1:9001", "holders": ["127.0.0.1:9001"],
         "bytes": 0}])
    res = m.lifecycle.run_pending(wait=True)
    assert res and res[0]["state"] == "failed", res
    assert "no live holder" in m.lifecycle.journal.get(
        "6:ttl_expire")["error"]


def test_shared_budget_withdrawable(tmp_path):
    """A master push of 0 restores the node's local scrub default
    instead of latching a stale cluster budget forever."""
    from seaweedfs_tpu.storage.scrub import Scrubber
    from seaweedfs_tpu.storage.store import Store

    store = Store([str(tmp_path)], needle_cache_mb=0)
    s = Scrubber(store, rate_mbps=4, interval_s=9999)
    local = s.bucket.rate
    s.set_shared_rate(2.0)
    assert s.bucket.rate == 2.0 * (1 << 20)
    assert s._shared_budget
    s.throttle_background(1)  # charges while the budget is active
    s.set_shared_rate(0.0)
    assert s.bucket.rate == local
    assert not s._shared_budget
    store.close()


def test_compact_refuses_remote_or_tiering_volume(tmp_path):
    from helpers import start_s3_stub

    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend
    from seaweedfs_tpu.storage.store import Store

    stub, _handler = start_s3_stub()
    try:
        endpoint = f"http://127.0.0.1:{stub.server_address[1]}"
        make_s3_backend("vacrt", {"endpoint": endpoint, "bucket": "b"})
        from helpers import make_volume

        make_volume(str(tmp_path), volume_id=23, n_needles=5).close()
        store = Store([str(tmp_path)], needle_cache_mb=0)
        v = store.find_volume(23)
        v.tier_to_remote("s3.vacrt")
        with pytest.raises(ValueError, match="remote-tiered"):
            store.compact_volume(23)
        store.close()
    finally:
        stub.shutdown()
        stub.server_close()


# ---------------------------------------------------------------------------
# pure balance planners (satellite: shared shell/controller planning)
# ---------------------------------------------------------------------------


def _topo(node_vols: dict[str, list[int]],
          max_count: int = 10) -> master_pb2.TopologyInfo:
    info = master_pb2.TopologyInfo(id="topo")
    dc = info.data_center_infos.add(id="dc1")
    rack = dc.rack_infos.add(id="r1")
    for nid, vids in node_vols.items():
        dn = rack.data_node_infos.add(id=nid)
        disk = dn.disk_infos[""]
        disk.volume_count = len(vids)
        disk.max_volume_count = max_count
        for vid in vids:
            disk.volume_infos.add(id=vid, size=10)
    return info


def test_plan_volume_balance_moves_evens_counts():
    from seaweedfs_tpu.shell.volume_commands import (
        plan_volume_balance_moves,
    )

    moves = plan_volume_balance_moves(_topo({
        "n1:80": [1, 2, 3, 4, 5, 6], "n2:80": [], "n3:80": [7],
    }))
    assert moves, "skewed cluster must plan moves"
    for mv in moves:
        assert mv["source"] == "n1:80"
    # model convergence: donor sheds down to ~avg+1
    assert len(moves) >= 2


def test_plan_volume_balance_skips_replica_holding_target():
    from seaweedfs_tpu.shell.volume_commands import (
        plan_volume_balance_moves,
    )

    # n2 already holds replicas of everything n1 has: no legal move
    moves = plan_volume_balance_moves(_topo({
        "n1:80": [1, 2, 3], "n2:80": [1, 2, 3], "n3:80": [],
    }))
    for mv in moves:
        assert mv["target"] != "n2:80" or mv["volumeId"] not in (1, 2, 3)


def test_plan_volume_balance_prefers_rack_diverse_move():
    from seaweedfs_tpu.shell.volume_commands import (
        plan_volume_balance_moves,
    )

    # two racks: donor n1 (r1) holds v1 (sibling replica on n3, which is
    # in the TARGET's rack r2) and v2 (sibling on n4 in r1).  Moving v2
    # to the r2 target adds rack diversity; moving v1 would stack both
    # of its replicas into r2.  The planner must prefer v2.
    info = master_pb2.TopologyInfo(id="topo")
    dc = info.data_center_infos.add(id="dc1")
    r1 = dc.rack_infos.add(id="r1")
    r2 = dc.rack_infos.add(id="r2")

    def add(rack, nid, vids):
        dn = rack.data_node_infos.add(id=nid)
        disk = dn.disk_infos[""]
        disk.volume_count = len(vids)
        disk.max_volume_count = 10
        for vid in vids:
            disk.volume_infos.add(id=vid, size=10)

    add(r1, "n1:80", [1, 2, 5, 6])
    add(r2, "n2:80", [])          # the underloaded target
    add(r2, "n3:80", [1, 5, 6])   # sibling of v1 already in r2
    add(r1, "n4:80", [2, 7])      # sibling of v2 in r1
    moves = plan_volume_balance_moves(info)
    to_n2 = [mv for mv in moves if mv["target"] == "n2:80"]
    assert to_n2, moves
    assert to_n2[0]["volumeId"] == 2, moves


def test_plan_volume_balance_balanced_is_empty():
    from seaweedfs_tpu.shell.volume_commands import (
        plan_volume_balance_moves,
    )

    assert plan_volume_balance_moves(_topo({
        "n1:80": [1, 2], "n2:80": [3, 4],
    })) == []
    assert plan_volume_balance_moves(_topo({})) == []


def test_plan_ec_balance_moves():
    from seaweedfs_tpu.shell.ec_commands import plan_ec_balance_moves

    info = master_pb2.TopologyInfo(id="topo")
    dc = info.data_center_infos.add(id="dc1")
    rack = dc.rack_infos.add(id="r1")
    d1 = rack.data_node_infos.add(id="n1:80").disk_infos[""]
    d1.max_volume_count = 10
    d1.ec_shard_infos.add(id=5, ec_index_bits=0x3FFF)  # all 14 shards
    d2 = rack.data_node_infos.add(id="n2:80").disk_infos[""]
    d2.max_volume_count = 10
    moves = plan_ec_balance_moves(info)
    assert moves, "one node holding all 14 shards must shed"
    assert all(mv["source"] == "n1:80" and mv["target"] == "n2:80"
               for mv in moves)
    sids = {mv["shardId"] for mv in moves}
    assert len(sids) == len(moves), "each shard moved at most once"
    # collection scoping filters everything out
    assert plan_ec_balance_moves(info, collection="other") == []


def test_rebalance_plans_from_controller(tmp_path):
    m = _mk_master(tmp_path, policy={"*": {"rebalance_skew": 2,
                                           "seal_full_percent": 0,
                                           "vacuum_garbage_ratio": 0,
                                           "ttl_expire": False}})
    now = int(time.time())
    vols = {i: VolumeInfo(i, size=100, modified_at_second=now - 5)
            for i in range(1, 7)}
    _add_node(m, "127.0.0.1:9001", vols)
    _add_node(m, "127.0.0.1:9002", {})
    plans = [p for p in m.lifecycle.evaluate()
             if p["transition"] == "rebalance"]
    assert plans, "6-0 skew with skew=2 must plan rebalance jobs"
    for p in plans:
        assert p["source"] == "127.0.0.1:9001"
        assert p["target"] == "127.0.0.1:9002"


def test_default_policy_plans_no_rebalance(tmp_path):
    m = _mk_master(tmp_path)
    now = int(time.time())
    vols = {i: VolumeInfo(i, size=100, modified_at_second=now - 5)
            for i in range(1, 7)}
    _add_node(m, "127.0.0.1:9001", vols)
    _add_node(m, "127.0.0.1:9002", {})
    assert [p for p in m.lifecycle.evaluate()
            if p["transition"] == "rebalance"] == []


# ---------------------------------------------------------------------------
# policy file persistence
# ---------------------------------------------------------------------------


def test_policy_file_persists_across_restart(tmp_path):
    m = _mk_master(tmp_path)
    m.lifecycle.set_policies({"*": {"rebalance_skew": 3}})
    assert os.path.exists(str(tmp_path / "lifecycle.policy.json"))
    m2 = _mk_master(tmp_path)
    assert m2.lifecycle.policies.for_collection("x").rebalance_skew == 3


def test_constructor_policy_overrides_file(tmp_path):
    m = _mk_master(tmp_path)
    m.lifecycle.set_policies({"*": {"rebalance_skew": 3}})
    m2 = _mk_master(tmp_path, policy={"*": {"rebalance_skew": 5}})
    assert m2.lifecycle.policies.for_collection("x").rebalance_skew == 5
    # and the explicit policy becomes the persisted one
    with open(str(tmp_path / "lifecycle.policy.json")) as f:
        assert json.load(f)["*"]["rebalance_skew"] == 5
