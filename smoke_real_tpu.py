#!/usr/bin/env python
"""Opt-in real-chip smoke test: compiled-Mosaic byte-identity in ~seconds.

CI runs the whole suite on a virtual CPU mesh (tests/conftest.py pins
JAX_PLATFORMS=cpu), so the Pallas kernel is only ever exercised in
interpreter mode there — compiled-Mosaic breakage on the real chip is
structurally invisible to CI.  This script is the gap-closer: it encodes
16MB through ``get_codec("tpu")`` on the real backend inside a
subprocess with a hard 120s bound, asserts byte-equality against the CPU
codec, and prints ONE JSON line either way.

Run it at round start and commit the output as SMOKE_r{N}.json:

    python smoke_real_tpu.py | tee SMOKE_r05.json

A wedged axon tunnel (see .claude/skills/verify/SKILL.md) shows up as
``{"ok": false, "error": "timeout ..."}`` — a true kernel regression as a
byte mismatch.  Exit code 0 iff ok.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_CHILD_FLAG = "--child"
_MB = 16


def _child() -> None:
    import numpy as np

    t0 = time.perf_counter()
    import jax.numpy as jnp

    from seaweedfs_tpu.ops.codec import get_codec

    rng = np.random.default_rng(0x5EED)
    data = rng.integers(0, 256, (10, _MB << 20), dtype=np.uint8)
    cpu = get_codec("cpu").parity_of(data)
    t_cpu = time.perf_counter() - t0

    tpu = get_codec("tpu")
    t0 = time.perf_counter()
    d3 = data.view(np.uint32).reshape(10, -1, 128)
    out = tpu.encode_device_u32_3d(jnp.asarray(d3))
    if out is None:
        out = tpu.encode_device(jnp.asarray(data))
        parity = np.asarray(out)
    else:
        parity = np.asarray(out).view(np.uint8).reshape(4, -1)
    t_tpu = time.perf_counter() - t0
    ok = bool(np.array_equal(parity, cpu))
    print(json.dumps({
        "ok": ok,
        "bytes": int(data.size),
        "cpu_seconds": round(t_cpu, 2),
        "tpu_seconds_inc_compile": round(t_tpu, 2),
        "backend": __import__("jax").devices()[0].platform,
    }))
    sys.exit(0 if ok else 1)


def main() -> int:
    if _CHILD_FLAG in sys.argv:
        _child()
        return 0
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), _CHILD_FLAG],
            capture_output=True, text=True, timeout=120,
        )
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "ok": False,
            "error": "timeout after 120s (axon tunnel wedged or chip busy)",
        }))
        return 1
    line = (proc.stdout.strip().splitlines() or ["{}"])[-1]
    try:
        parsed = json.loads(line)
    except ValueError:
        parsed = {"ok": False,
                  "error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
    print(json.dumps(parsed))
    return 0 if parsed.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
