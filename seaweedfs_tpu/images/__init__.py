"""Image resize + EXIF orientation fix on read.

Reference: weed/images/resizing.go (?width/?height/?mode= on image GETs)
and orientation.go (JPEGs re-oriented per their EXIF tag before being
served).  Pillow replaces the imaging/Go stdlib pipeline; behavior
parity: mode "fit" preserves aspect inside the box, "fill" crops to
exactly fill it, default resizes to the requested dimensions (square
default on non-square input thumbnails, like imaging.Thumbnail).
"""

from __future__ import annotations

import io

IMAGE_EXTS = {".jpg", ".jpeg", ".png", ".gif", ".webp"}
IMAGE_MIMES = {"image/jpeg", "image/png", "image/gif", "image/webp"}


def is_image(ext: str = "", mime: str = "") -> bool:
    return ext.lower() in IMAGE_EXTS or mime.lower() in IMAGE_MIMES


def fix_orientation(data: bytes) -> bytes:
    """Apply the EXIF orientation tag (JPEG) and strip it
    (orientation.go FixJpgOrientation)."""
    try:
        from PIL import Image, ImageOps

        img = Image.open(io.BytesIO(data))
        if img.format != "JPEG":
            return data
        # only pay a re-encode when an actual rotation is recorded —
        # exif_transpose returns a copy even for orientation-free files,
        # so the tag itself is the no-op check
        if img.getexif().get(0x0112, 1) in (None, 0, 1):
            return data
        fixed = ImageOps.exif_transpose(img)
        out = io.BytesIO()
        fixed.save(out, format="JPEG", quality=90)
        return out.getvalue()
    except Exception:
        return data


def resized(data: bytes, ext: str, width: int = 0, height: int = 0,
            mode: str = "") -> tuple[bytes, int, int]:
    """-> (bytes, w, h); returns the input untouched when no resize
    applies (resizing.go Resized)."""
    if not width and not height:
        return data, 0, 0
    try:
        from PIL import Image, ImageOps

        img = Image.open(io.BytesIO(data))
        bw, bh = img.size
        if not ((width and bw > width) or (height and bh > height)):
            return data, bw, bh
        if mode == "fit":
            img.thumbnail((width or bw, height or bh),
                          Image.Resampling.LANCZOS)
            dst = img
        elif mode == "fill":
            dst = ImageOps.fit(img, (width or bw, height or bh),
                               Image.Resampling.LANCZOS)
        else:
            if width and height and width == height and bw != bh:
                dst = ImageOps.fit(img, (width, height),
                                   Image.Resampling.LANCZOS)
            else:
                # zero dimension: scale preserving aspect
                if not width:
                    width = max(1, bw * height // bh)
                if not height:
                    height = max(1, bh * width // bw)
                dst = img.resize((width, height),
                                 Image.Resampling.LANCZOS)
        fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG",
               "gif": "GIF", "webp": "WEBP"}.get(
            ext.lower().lstrip("."), img.format or "PNG")
        out = io.BytesIO()
        if fmt == "JPEG" and dst.mode not in ("RGB", "L"):
            dst = dst.convert("RGB")
        dst.save(out, format=fmt)
        return out.getvalue(), dst.size[0], dst.size[1]
    except Exception:
        return data, 0, 0
