"""Pub/sub message broker plane.

Reference: weed/messaging/broker — topics persisted as filer log files,
partition->broker assignment by consistent hashing, gRPC publish/subscribe
streams (weed/pb/messaging.proto).
"""

from .broker import MessageBrokerServer

__all__ = ["MessageBrokerServer"]
