"""Message broker: gRPC pub/sub with filer-backed topic persistence.

Reference: weed/messaging/broker/broker_server.go:24 (broker process
bound to a filer), broker_grpc_server_publish.go / _subscribe.go
(client-stream publish, server-stream subscribe with ack),
consistent_distribution.go (partition -> broker via consistent hashing),
topic_manager.go (per-partition in-memory log + filer segment files under
/topics/<namespace>/<topic>/).

Persistence model: every partition appends length-prefixed serialized
Messages to a filer file /topics/<ns>/<topic>/p<NN>.log (the reference's
log-file segments).  On first open a partition replays its file into
memory, so subscribers can start from EARLIEST across broker restarts.
"""

from __future__ import annotations

import hashlib
import struct
import threading
import urllib.error
import urllib.parse

import grpc

from ..pb import messaging_pb2 as mq
from ..pb import rpc as rpclib
from ..util import connpool, glog

TOPICS_DIR = "/topics"


def hash_ring_owner(brokers: list[str], key: str) -> str:
    """Deterministic partition->broker assignment: highest-random-weight
    (rendezvous) hashing — same distribution contract as the reference's
    consistent-hash ring with simpler machinery."""
    if not brokers:
        raise ValueError("no brokers")
    return max(
        brokers,
        key=lambda b: hashlib.sha256(f"{b}|{key}".encode()).digest(),
    )


class TopicPartition:
    """One partition: in-memory message list + filer-backed log file."""

    def __init__(self, namespace: str, topic: str, partition: int,
                 filer_http: str = ""):
        self.key = f"{namespace}/{topic}/{partition}"
        self.filer_http = filer_http
        self.filer_path = (
            f"{TOPICS_DIR}/{namespace}/{topic}/p{partition:02d}.log"
        )
        self.messages: list[mq.Message] = []
        self.cond = threading.Condition()
        self._loaded = False
        self._pending: list[bytes] = []  # serialized, not yet persisted
        # serializes whole take-pending-and-append sequences: flush() can be
        # entered from both _flush_loop and stop(), and two in-flight appends
        # could land out of publish order in the filer log
        self._flush_lock = threading.Lock()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        if self._loaded or not self.filer_http:
            self._loaded = True
            return
        self._loaded = True
        try:
            url = (f"http://{self.filer_http}"
                   f"{urllib.parse.quote(self.filer_path)}")
            with connpool.request("GET", url, timeout=30) as r:
                blob = r.read()
        except OSError:  # incl. HTTPError / connection refused
            return
        pos = 0
        while pos + 4 <= len(blob):
            (ln,) = struct.unpack(">I", blob[pos : pos + 4])
            pos += 4
            if pos + ln > len(blob):
                break
            m = mq.Message()
            try:
                m.ParseFromString(blob[pos : pos + ln])
            except Exception:
                break
            self.messages.append(m)
            pos += ln

    def flush(self) -> None:
        """Write batched records to the filer log in ONE append — per-
        message HTTP roundtrips would make publish latency a full filer
        write and create one volume chunk per message."""
        with self._flush_lock:
            with self.cond:
                pending, self._pending = self._pending, []
            if not pending or not self.filer_http:
                return
            data = b"".join(pending)
            url = (f"http://{self.filer_http}"
                   f"{urllib.parse.quote(self.filer_path)}?op=append")
            try:
                with connpool.request(
                        "POST", url, body=data,
                        headers={"Content-Type":
                                 "application/octet-stream"},
                        timeout=30) as r:
                    r.read()
            except Exception as e:
                glog.warning("broker: persist %s failed: %s", self.key, e)
                with self.cond:  # retry on the next flush tick
                    self._pending = pending + self._pending

    # -- pub/sub -----------------------------------------------------------

    def publish(self, m: mq.Message) -> int:
        blob = m.SerializeToString()
        with self.cond:
            self._load()
            self.messages.append(m)
            idx = len(self.messages) - 1
            self._pending.append(struct.pack(">I", len(blob)) + blob)
            self.cond.notify_all()
        return idx

    def start_index(self, init: mq.SubscriberMessage.InitMessage) -> int:
        with self.cond:
            self._load()
            sp = init.startPosition
            if sp == mq.SubscriberMessage.InitMessage.EARLIEST:
                return 0
            if sp == mq.SubscriberMessage.InitMessage.TIMESTAMP:
                for i, m in enumerate(self.messages):
                    if m.event_time_ns >= init.timestampNs:
                        return i
                return len(self.messages)
            return len(self.messages)  # LATEST

    def read_from(self, index: int, stop: threading.Event):
        """Yield (index, message) from index onward; tails live."""
        while not stop.is_set():
            with self.cond:
                self._load()
                if index < len(self.messages):
                    m = self.messages[index]
                else:
                    self.cond.wait(timeout=0.2)
                    continue
            yield index, m
            index += 1


class MessageBrokerGrpcService:
    def __init__(self, server: "MessageBrokerServer"):
        self.server = server

    def _partition(self, ns: str, topic: str, p: int) -> TopicPartition:
        return self.server.get_partition(ns, topic, p)

    def Publish(self, request_iterator, context):
        init = None
        part: TopicPartition | None = None
        for req in request_iterator:
            if req.HasField("init"):
                init = req.init
                owner = self.server.owner_of(
                    init.namespace, init.topic, init.partition
                )
                if owner != self.server.grpc_address:
                    yield mq.PublishResponse(
                        redirect=mq.PublishResponse.RedirectMessage(
                            new_broker=owner
                        )
                    )
                    return
                part = self._partition(
                    init.namespace, init.topic, init.partition
                )
                conf = self.server.topic_configuration(
                    init.namespace, init.topic
                )
                yield mq.PublishResponse(
                    config=mq.PublishResponse.ConfigMessage(
                        partition_count=conf.partition_count or 1
                    )
                )
                continue
            if part is None:
                context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                              "publish before init")
            if req.data.is_close:
                break
            part.publish(req.data)
        yield mq.PublishResponse(is_closed=True)

    def Subscribe(self, request_iterator, context):
        it = iter(request_iterator)
        first = next(it, None)
        if first is None or not first.HasField("init"):
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "first message must be init")
        init = first.init
        part = self._partition(init.namespace, init.topic, init.partition)
        stop = threading.Event()
        context.add_callback(stop.set)

        def drain_acks():
            try:
                for req in it:
                    if req.is_close:
                        return
            except Exception:
                pass  # client went away; the context callback stops us
            finally:
                stop.set()

        threading.Thread(target=drain_acks, daemon=True).start()
        for _idx, m in part.read_from(part.start_index(init), stop):
            yield mq.BrokerMessage(data=m)
            if m.is_close:
                return

    def DeleteTopic(self, request, context):
        self.server.delete_topic(request.namespace, request.topic)
        return mq.DeleteTopicResponse()

    def ConfigureTopic(self, request, context):
        self.server.configure_topic(
            request.namespace, request.topic, request.configuration
        )
        return mq.ConfigureTopicResponse()

    def GetTopicConfiguration(self, request, context):
        resp = mq.GetTopicConfigurationResponse()
        resp.configuration.CopyFrom(
            self.server.topic_configuration(request.namespace, request.topic)
        )
        return resp

    def FindBroker(self, request, context):
        owner = self.server.owner_of(
            request.namespace, request.topic, request.parition
        )
        return mq.FindBrokerResponse(broker=owner)


class MessageBrokerServer:
    """`weed msgBroker` equivalent: one broker process bound to a filer."""

    def __init__(self, filer: str = "", port: int = 17777,
                 ip: str = "127.0.0.1", peers: list[str] | None = None):
        self.ip = ip
        self.port = port
        self.grpc_address = f"{ip}:{port}"
        self.filer_http = filer
        # quorum of brokers for partition ownership; defaults to just us
        self.brokers = sorted(set((peers or []) + [self.grpc_address]))
        self._partitions: dict[str, TopicPartition] = {}
        self._topic_conf: dict[str, mq.TopicConfiguration] = {}
        self._lock = threading.Lock()
        self._grpc_server = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._grpc_server = rpclib.serve(
            [(rpclib.MESSAGING, MessageBrokerGrpcService(self))], self.port
        )
        threading.Thread(target=self._flush_loop, daemon=True).start()
        glog.info("message broker started grpc=%d filer=%s brokers=%s",
                  self.port, self.filer_http, self.brokers)

    def stop(self) -> None:
        self._stop.set()
        self.flush()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)

    def flush(self) -> None:
        with self._lock:
            parts = list(self._partitions.values())
        for part in parts:
            part.flush()

    def _flush_loop(self, interval: float = 0.2) -> None:
        while not self._stop.wait(interval):
            self.flush()

    # -- topics ------------------------------------------------------------

    def get_partition(self, ns: str, topic: str, p: int) -> TopicPartition:
        key = f"{ns}/{topic}/{p}"
        with self._lock:
            part = self._partitions.get(key)
            if part is None:
                part = TopicPartition(ns, topic, p, self.filer_http)
                self._partitions[key] = part
            return part

    def owner_of(self, ns: str, topic: str, partition: int) -> str:
        return hash_ring_owner(self.brokers, f"{ns}/{topic}/{partition}")

    def topic_configuration(self, ns: str, topic: str) -> mq.TopicConfiguration:
        with self._lock:
            conf = self._topic_conf.get(f"{ns}/{topic}")
            if conf is None:
                conf = mq.TopicConfiguration(partition_count=1)
            return conf

    def configure_topic(self, ns: str, topic: str,
                        conf: mq.TopicConfiguration) -> None:
        stored = mq.TopicConfiguration()
        stored.CopyFrom(conf)
        with self._lock:
            self._topic_conf[f"{ns}/{topic}"] = stored

    def delete_topic(self, ns: str, topic: str) -> None:
        prefix = f"{ns}/{topic}/"
        with self._lock:
            for key in [k for k in self._partitions if k.startswith(prefix)]:
                del self._partitions[key]
            self._topic_conf.pop(f"{ns}/{topic}", None)
        if self.filer_http:
            url = (f"http://{self.filer_http}"
                   f"{urllib.parse.quote(f'{TOPICS_DIR}/{ns}/{topic}')}"
                   "?recursive=true&ignoreRecursiveError=true")
            try:
                with connpool.request("DELETE", url, timeout=30) as r:
                    r.read()
            except urllib.error.HTTPError:
                pass
