"""Per-path filer configuration (storage rules by location prefix).

Reference: weed/filer/filer_conf.go — rules stored INSIDE the filer at
/etc/seaweedfs/filer.conf; each rule assigns collection/replication/ttl
to writes under a path prefix, longest prefix wins.  The reference keeps
a ptrie and jsonpb text; here rules live in a JSON document and matching
is a linear longest-prefix scan (rule counts are tiny).
"""

from __future__ import annotations

import json
import time

CONF_DIR = "/etc/seaweedfs"
CONF_NAME = "filer.conf"
CONF_PATH = f"{CONF_DIR}/{CONF_NAME}"


class PathConf(dict):
    """A rule: {locationPrefix, collection, replication, ttl}."""

    @property
    def location_prefix(self) -> str:
        return self.get("locationPrefix", "")


class FilerConf:
    def __init__(self, rules: list[dict] | None = None):
        self.rules = [PathConf(r) for r in (rules or [])
                      if r.get("locationPrefix")]

    @classmethod
    def from_bytes(cls, raw: bytes) -> "FilerConf":
        try:
            doc = json.loads(raw) if raw else {}
        except ValueError:
            doc = {}
        if not isinstance(doc, dict):
            doc = {}
        rules = doc.get("locations", [])
        if not isinstance(rules, list):
            rules = []
        return cls([r for r in rules if isinstance(r, dict)])

    def to_bytes(self) -> bytes:
        return json.dumps({"locations": self.rules}, indent=2).encode()

    def upsert(self, rule: dict) -> None:
        self.delete(rule.get("locationPrefix", ""))
        self.rules.append(PathConf(rule))

    def delete(self, location_prefix: str) -> None:
        self.rules = [r for r in self.rules
                      if r.location_prefix != location_prefix]

    def match(self, path: str) -> PathConf | None:
        """Longest matching locationPrefix rule for a write path."""
        best = None
        for r in self.rules:
            p = r.location_prefix
            if path.startswith(p) and \
                    (best is None or len(p) > len(best.location_prefix)):
                best = r
        return best


class FilerConfHolder:
    """Lazily (re)loads the conf through a `read_fn(path) -> bytes|None`
    with a small TTL — rule edits through fs.configure take effect within
    `refresh_seconds` on every filer write path."""

    def __init__(self, read_fn, refresh_seconds: float = 2.0):
        self.read_fn = read_fn
        self.refresh_seconds = refresh_seconds
        self._conf = FilerConf()
        self._loaded_at = 0.0

    def get(self) -> FilerConf:
        now = time.monotonic()
        if now - self._loaded_at > self.refresh_seconds:
            try:
                raw = self.read_fn(CONF_PATH) or b""
            except Exception:
                raw = b""
            self._conf = FilerConf.from_bytes(raw)
            self._loaded_at = now
        return self._conf

    def match(self, path: str) -> PathConf | None:
        return self.get().match(path)
