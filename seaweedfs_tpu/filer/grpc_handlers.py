"""Filer gRPC service (filer_pb.SeaweedFiler, 19 rpcs).

Reference: weed/server/filer_grpc_server*.go.
"""

from __future__ import annotations

import threading

import grpc

from ..pb import filer_pb2
from .filer import join_path
from .fleet.tenant import QuotaExceededError


class FilerGrpcService:
    def __init__(self, filer_server):
        self.fs = filer_server

    @property
    def filer(self):
        return self.fs.filer

    # -- metadata ----------------------------------------------------------

    def LookupDirectoryEntry(self, request, context):
        # the Filer path (not the raw store) so hardlink stubs come back
        # merged with their shared KV meta (filerstore_hardlink.go)
        entry = self.filer._maybe_read_hardlink(
            self.filer.store.find_entry(request.directory, request.name))
        if entry is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{join_path(request.directory, request.name)} not found")
        resp = filer_pb2.LookupDirectoryEntryResponse()
        resp.entry.CopyFrom(entry)
        return resp

    def ListEntries(self, request, context):
        limit = request.limit or 1024
        for e in self.filer.list_directory(
            request.directory,
            start_from=request.start_from_file_name,
            inclusive=request.inclusive_start_from,
            prefix=request.prefix,
            limit=limit,
        ):
            resp = filer_pb2.ListEntriesResponse()
            resp.entry.CopyFrom(e)
            yield resp

    def _maybe_manifestize(self, directory, entry) -> None:
        """Fold over-long chunk lists before the store write
        (filer_grpc_server.go MaybeManifestize)."""
        folded = self.fs.manifestize_chunks(
            list(entry.chunks), path=join_path(directory, entry.name)
        )
        if len(folded) != len(entry.chunks):
            del entry.chunks[:]
            entry.chunks.extend(folded)

    def CreateEntry(self, request, context):
        try:
            self._maybe_manifestize(request.directory, request.entry)
            self.filer.create_entry(
                request.directory, request.entry, o_excl=request.o_excl,
                signatures=list(request.signatures),
            )
            return filer_pb2.CreateEntryResponse()
        except FileExistsError as e:
            return filer_pb2.CreateEntryResponse(error=str(e))
        except QuotaExceededError as e:
            # the "quota exceeded" prefix is the wire contract the S3
            # gateway maps to 403 QuotaExceeded XML
            return filer_pb2.CreateEntryResponse(error=str(e))

    def UpdateEntry(self, request, context):
        try:
            self._maybe_manifestize(request.directory, request.entry)
            self.filer.update_entry(request.directory, request.entry,
                                    signatures=list(request.signatures))
        except FileNotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except QuotaExceededError as e:
            context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        return filer_pb2.UpdateEntryResponse()

    def AppendToEntry(self, request, context):
        try:
            self.filer.append_chunks(
                request.directory, request.entry_name, list(request.chunks)
            )
        except QuotaExceededError as e:
            context.abort(grpc.StatusCode.PERMISSION_DENIED, str(e))
        entry = self.filer.store.find_entry(request.directory,
                                            request.entry_name)
        if entry is not None and len(entry.chunks) > self.fs.manifest_batch:
            self._maybe_manifestize(request.directory, entry)
            self.filer.update_entry(request.directory, entry)
        return filer_pb2.AppendToEntryResponse()

    def DeleteEntry(self, request, context):
        try:
            self.filer.delete_entry(
                request.directory,
                request.name,
                is_recursive=request.is_recursive,
                ignore_recursive_error=request.ignore_recursive_error,
                is_delete_data=request.is_delete_data,
                signatures=list(request.signatures),
            )
            return filer_pb2.DeleteEntryResponse()
        except FileNotFoundError as e:
            # distinguishable marker: callers (S3 multi-delete) treat a
            # missing key as already-deleted, AWS-style
            return filer_pb2.DeleteEntryResponse(error=f"not found: {e}")
        except IsADirectoryError as e:
            return filer_pb2.DeleteEntryResponse(error=str(e))

    def AtomicRenameEntry(self, request, context):
        try:
            self.filer.rename_entry(
                request.old_directory, request.old_name,
                request.new_directory, request.new_name,
            )
        except (FileNotFoundError, FileExistsError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return filer_pb2.AtomicRenameEntryResponse()

    # -- cluster proxies ---------------------------------------------------

    def AssignVolume(self, request, context):
        collection = request.collection or self.filer.bucket_collection(
            request.path
        )
        # filer.conf path rules fill whatever the client left unset
        from .server import _ttl_seconds

        collection, replication, rule_ttl = self.fs.apply_path_conf(
            request.path, collection, request.replication,
            "set" if request.ttl_sec else "")
        try:
            result = self.fs.assign(
                count=request.count or 1,
                collection=collection,
                replication=replication,
                ttl_sec=request.ttl_sec or _ttl_seconds(rule_ttl),
                data_center=request.data_center,
                rack=request.rack,
            )
        except Exception as e:
            return filer_pb2.AssignVolumeResponse(error=str(e))
        return filer_pb2.AssignVolumeResponse(
            file_id=result.fid,
            url=result.url,
            public_url=result.public_url,
            count=result.count,
            auth=result.auth,
            collection=collection,
            replication=request.replication,
        )

    def LookupVolume(self, request, context):
        resp = filer_pb2.LookupVolumeResponse()
        for vid_s in request.volume_ids:
            try:
                vid = int(vid_s.split(",", 1)[0])
            except ValueError:
                continue
            locs = filer_pb2.Locations()
            for l in self.fs.master_client.lookup_volume(vid):
                locs.locations.append(
                    filer_pb2.Location(url=l.url, public_url=l.public_url)
                )
            resp.locations_map[vid_s].CopyFrom(locs)
        return resp

    def CollectionList(self, request, context):
        resp = filer_pb2.CollectionListResponse()
        seen = set()
        for e in self.filer.list_directory("/buckets", limit=10000):
            if e.is_directory and e.name not in seen:
                seen.add(e.name)
                resp.collections.add(name=e.name)
        return resp

    def DeleteCollection(self, request, context):
        self.fs.delete_collection(request.collection)
        return filer_pb2.DeleteCollectionResponse()

    def Statistics(self, request, context):
        return filer_pb2.StatisticsResponse(
            total_size=0, used_size=0, file_count=0
        )

    def GetFilerConfiguration(self, request, context):
        return filer_pb2.GetFilerConfigurationResponse(
            masters=self.fs.masters,
            max_mb=self.fs.max_mb,
            dir_buckets="/buckets",
            collection="",
            replication=self.fs.default_replication,
            signature=self.fs.signature,
            cipher=self.fs.cipher,
        )

    # -- metadata subscription ---------------------------------------------

    @staticmethod
    def _subscribe_log(log, request, context):
        stop = threading.Event()
        context.add_callback(stop.set)
        for ev in log.subscribe(
            request.since_ns, request.path_prefix, stop_event=stop
        ):
            if request.signature and request.signature in ev.event_notification.signatures:
                continue  # skip events this subscriber itself caused
            yield ev

    def SubscribeMetadata(self, request, context):
        """The merged stream: with filer peers configured this reads the
        MetaAggregator's log (events from every peer, self included);
        stand-alone it reads the local log directly."""
        agg = self.fs.meta_aggregator
        log = agg.log if agg is not None else self.filer.meta_log
        yield from self._subscribe_log(log, request, context)

    def SubscribeLocalMetadata(self, request, context):
        """Only THIS filer's own mutations (filer.proto:58) — what peer
        MetaAggregators tail; never includes replayed peer events, which
        is what keeps replication loop-free.

        The in-memory log is bounded and dies with the process (the
        reference replays from its persisted /topics/.system/log files);
        when the subscriber asks for history older than the log can
        serve, the CURRENT STORE is streamed first as synthetic create
        events — replays are idempotent upserts, so a follower converges
        on the full namespace even across restarts/eviction.  Deletions
        that happened entirely inside the lost window stay unreplicated
        (documented divergence from the persisted-log design)."""
        log = self.filer.meta_log
        if request.since_ns < log.history_start_ns():
            yield from self._snapshot_events(request.path_prefix)
        yield from self._subscribe_log(log, request, context)

    def _snapshot_events(self, path_prefix: str):
        """BFS of the store as create events, emitted in STRICTLY
        INCREASING ts order (base: each entry's mtime) — consumers
        (MetaAggregator.ingest gate, resume watermarks) assume a
        monotonic stream."""
        store = self.filer.store
        collected: list[tuple[int, str, filer_pb2.Entry]] = []
        queue = ["/"]
        while queue:
            d = queue.pop(0)
            start = ""
            while True:
                batch = list(store.list_entries(d, start_from=start,
                                                limit=1024))
                if not batch:
                    break
                for e in batch:
                    child = d.rstrip("/") + "/" + e.name
                    if e.is_directory:
                        queue.append(child)
                    if path_prefix and not (
                        child.startswith(path_prefix)
                        or path_prefix.startswith(child + "/")
                    ):
                        continue
                    ts = (e.attributes.mtime or 1) * 1_000_000_000
                    collected.append((ts, d, e))
                start = batch[-1].name
        last_ts = 0
        for ts, d, e in sorted(collected, key=lambda x: (x[0], x[1])):
            ts = max(ts, last_ts + 1)
            last_ts = ts
            resp = filer_pb2.SubscribeMetadataResponse(
                directory=d, ts_ns=ts)
            resp.event_notification.new_entry.CopyFrom(e)
            yield resp

    def KeepConnected(self, request_iterator, context):
        for req in request_iterator:
            yield filer_pb2.KeepConnectedResponse()

    def LocateBroker(self, request, context):
        return self.fs.locate_broker(request.resource)

    # -- KV ----------------------------------------------------------------

    def KvGet(self, request, context):
        value = self.filer.store.kv_get(bytes(request.key))
        if value is None:
            return filer_pb2.KvGetResponse(error="not found")
        return filer_pb2.KvGetResponse(value=value)

    def KvPut(self, request, context):
        self.filer.store.kv_put(bytes(request.key), bytes(request.value))
        return filer_pb2.KvPutResponse()
