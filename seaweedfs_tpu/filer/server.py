"""FilerServer: HTTP + gRPC front over the Filer core, talking to the
cluster through a MasterClient.

Reference: weed/server/filer_server.go + filer_server_handlers_write*.go.
Uploads are auto-chunked: each max_mb slice gets its own Assign + direct
volume-server upload, then one CreateEntry records the chunk list
(filer_server_handlers_write_autochunk.go:24-69).
"""

from __future__ import annotations

import os
import random
import struct
import threading
import time

from ..operation import delete_file_ids, download, upload_data
from ..telemetry import trace
from ..util import failsafe, faultpoint, glog
from ..operation.assign import AssignResult, assign_any
from ..pb import filer_pb2
from ..pb import rpc as rpclib
from ..util.chunk_cache import TieredChunkCache
from ..util.executors import MeteredThreadPoolExecutor
from ..wdclient import MasterClient
from . import filechunk_manifest, filechunks
from .filer import Filer, split_path
from .filerstore import make_store
from .grpc_handlers import FilerGrpcService
from .http_handlers import serve_http

from ..util.http_util import grpc_address as _peer_grpc_addr
from ..util.http_util import netloc as _netloc

GRPC_PORT_OFFSET = 10000

FP_CHUNK_FETCH = faultpoint.register("filer.chunk.fetch")

# total budget for one chunk read INCLUDING all failover rounds: clamps
# every nested lookup rpc and download attempt via the ambient deadline
CHUNK_READ_DEADLINE_S = float(
    os.environ.get("SEAWEEDFS_TPU_CHUNK_READ_DEADLINE_S", "30"))


class FilerServer:
    def __init__(
        self,
        masters: list[str],  # master gRPC addresses
        ip: str = "127.0.0.1",
        port: int = 8888,
        store: str = "sqlite",
        store_path: str = "./filer.db",
        max_mb: int = 4,
        default_replication: str = "",
        metrics_port: int = 0,
        notification=None,  # notification.Publisher, or None
        chunk_cache_dir: str = "",
        chunk_cache_mem_mb: int = 32,
        manifest_batch: int = filechunk_manifest.MANIFEST_BATCH,
        peers: list[str] | None = None,  # peer filer HTTP addresses
        cipher: bool = False,  # AES-GCM encrypt chunk blobs (cipher.go)
        store_options: dict | None = None,  # extra store kwargs (redis etc.)
        cluster_id: int = 0,  # geo: this cluster's identity (nonzero = geo on)
        geo_peers: list[str] | None = None,  # remote cluster filer http addrs
        geo_rate_mbps: float | None = None,  # per-link budget; None = env
        meta_log_dir: str = "",  # durable event log dir; "" = derived
    ):
        self.masters = list(masters)
        self.ip = ip
        self.port = port
        self.grpc_port = port + GRPC_PORT_OFFSET
        self.max_mb = max_mb
        self.default_replication = default_replication
        self.cipher = cipher
        self.peers = [p.strip() for p in (peers or []) if p.strip()]
        for p in self.peers:
            peer_host, _, peer_port = p.partition(":")
            if not peer_host or not peer_port.isdigit():
                raise ValueError(
                    f"filer peer {p!r} must be host:port (http address)")
        self.metrics_port = metrics_port
        self.master_client = MasterClient(
            f"filer@{ip}:{port}", self.masters,
            client_type="filer", http_address=f"{ip}:{port}")
        opts = dict(store_options or {})
        # durable metadata event log (ISSUE 12): sequence-numbered
        # segments beside the store, so the geo replicator (and
        # within-cluster followers) resume across restarts with gap
        # detection instead of today's lossy in-memory ring.  Memory
        # stores stay memory-logged unless a dir is forced.  Per-append
        # fsync is paid only by geo-enabled filers (a non-geo filer's
        # log survives process SIGKILL via the page cache; host power
        # loss degrades to torn-tail truncation + gap-driven resync) —
        # SEAWEEDFS_TPU_META_LOG_FSYNC overrides either default.
        geo_on = cluster_id != 0 or bool(geo_peers)
        log_fsync = (None if "SEAWEEDFS_TPU_META_LOG_FSYNC" in os.environ
                     else geo_on)
        log_dir = meta_log_dir or None
        if log_dir is None and store != "memory" and not os.environ.get(
                "SEAWEEDFS_TPU_META_LOG_DISABLE"):
            log_dir = f"{store_path}.metalog"
        if store == "memory":
            self.filer = Filer(make_store("memory"), self._delete_chunks,
                               resolve_chunks_fn=self.resolve_chunks,
                               meta_log_dir=meta_log_dir or None,
                               meta_log_fsync=log_fsync)
        else:
            self.filer = Filer(
                make_store(store, path=store_path, **opts),
                self._delete_chunks,
                resolve_chunks_fn=self.resolve_chunks,
                meta_log_dir=log_dir,
                meta_log_fsync=log_fsync,
            )
        # tenant plane (fleet): quotas checked in the Filer mutation
        # path, WFQ admission consulted by the HTTP serving layer.
        # Config/usage persist in this shard's own store KV.
        from .fleet.tenant import AdmissionController, TenantManager

        self.tenants = TenantManager(self.filer.store)
        self.filer.tenants = self.tenants
        self.admission = AdmissionController(self.tenants)
        # the store signature identifies THIS store across restarts
        # (meta_aggregator.go: "filer.store.id"); peers replicate only
        # from stores whose signature differs from their own
        sig_raw = self.filer.store.kv_get(b"filer.store.id")
        if sig_raw and len(sig_raw) == 4:
            self.signature = struct.unpack(">i", sig_raw)[0]
        else:
            self.signature = random.randint(1, 2**31 - 1)
            self.filer.store.kv_put(b"filer.store.id",
                                    struct.pack(">i", self.signature))
        self.meta_aggregator = None
        if self.peers:
            from .meta_aggregator import MetaAggregator

            self.meta_aggregator = MetaAggregator(
                self.filer.store, self.signature,
                f"{ip}:{self.grpc_port}",
                [_peer_grpc_addr(p) for p in self.peers],
            )
        # geo plane (ISSUE 12): active-active cross-cluster replication.
        # A nonzero cluster id turns on HLC stamping + delete tombstones
        # (the LWW substrate) and the /.geo/* surface; each geo peer gets
        # its own replicator link with a journaled checkpoint.
        self.geo_peers = [p.strip() for p in (geo_peers or [])
                          if p.strip()]
        self.filer.cluster_id = cluster_id
        self.filer.geo_stamp = bool(self.geo_peers) or cluster_id != 0
        self.geo_applier = None
        self.geo_replicators = []
        if self.filer.geo_stamp:
            from ..replication.geo import GeoApplier, GeoReplicator

            self.geo_applier = GeoApplier(self)
            geo_dir = (f"{store_path}.geo" if store != "memory" else None)
            self.geo_replicators = [
                GeoReplicator(self, peer, journal_dir=geo_dir,
                              rate_mbps=geo_rate_mbps)
                for peer in self.geo_peers
            ]
        self._brokers: dict[str, list[str]] = {}
        self._grpc_server = None
        self._httpd = None
        # chunk fan-out (parallel chunk uploads + chunk-view reads):
        # saturation visible as seaweedfs_executor_*{executor="filer_chunk"}
        self._pool = MeteredThreadPoolExecutor(
            max_workers=8, name="filer_chunk")
        # tiered read cache + manifest batching (reader_at.go:88-104,
        # filechunk_manifest.go)
        self.chunk_cache = TieredChunkCache(
            mem_limit_bytes=chunk_cache_mem_mb << 20,
            mem_max_entry=max_mb << 20,
            disk_dir=chunk_cache_dir or None,
        )
        self.manifest_batch = manifest_batch
        # per-path storage rules at /etc/seaweedfs/filer.conf
        # (filer_conf.go); consulted on every write without explicit
        # collection/replication/ttl
        from .filer_conf import FilerConfHolder

        def _read_conf(path: str) -> bytes | None:
            d, n = split_path(path)
            entry = self.filer.store.find_entry(d, n)
            if entry is None:
                return None
            if entry.content:
                return bytes(entry.content)
            return self.read_entry_range(
                entry, 0, filechunks.total_size(entry.chunks))

        self.filer_conf = FilerConfHolder(_read_conf)
        self.notification = notification
        if notification is not None:
            # every metadata mutation fans out to the configured queue
            # (filer_notify.go -> notification.Queue.SendMessage).
            # Publishing happens on a dedicated worker: listeners run
            # under the meta-log lock, and a slow network backend (SQS,
            # Pub/Sub) must never stall metadata mutations.
            import queue as _queue

            self._notify_q: _queue.Queue = _queue.Queue(maxsize=4096)

            def _enqueue(resp):
                try:
                    self._notify_q.put_nowait(resp)
                except _queue.Full:
                    glog.warning("notification queue full; dropping event")

            def _drain():
                while True:
                    resp = self._notify_q.get()
                    if resp is None:
                        return
                    n = resp.event_notification
                    name = n.new_entry.name or n.old_entry.name
                    key = f"{resp.directory.rstrip('/')}/{name}"
                    try:
                        notification.publish(key, n)
                    except Exception as e:  # noqa: BLE001
                        glog.error("notification publish %s: %s", key, e)

            self.filer.meta_log.add_listener(_enqueue)
            threading.Thread(target=_drain, daemon=True,
                             name="filer-notify").start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        from ..stats.metrics import serve_metrics
        from ..util import glog

        self.master_client.start()
        # flight-recorder plane: always-on low-hz stack sampler feeding
        # /debug/profile/history (kill-switch + hz env knobs respected)
        from ..util import profiler as _profiler

        _profiler.ensure_continuous()
        self._grpc_server = rpclib.serve(
            [(rpclib.FILER, FilerGrpcService(self))], self.grpc_port
        )
        self._httpd = serve_http(self, "0.0.0.0", self.port)
        if self.metrics_port:
            self._metricsd = serve_metrics(self.metrics_port)
        if self.meta_aggregator is not None:
            self.meta_aggregator.start()
        for rep in self.geo_replicators:
            rep.start()
        glog.info("filer started http=%d grpc=%d peers=%d geo_links=%d",
                  self.port, self.grpc_port, len(self.peers),
                  len(self.geo_replicators))

    def stop(self) -> None:
        for rep in self.geo_replicators:
            rep.stop()
        if self.geo_applier is not None:
            self.geo_applier.flush()  # persist watermarks before close
        if self.meta_aggregator is not None:
            self.meta_aggregator.stop()
        self.master_client.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if getattr(self, "_metricsd", None):
            self._metricsd.shutdown()
            self._metricsd.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        self.tenants.close()  # checkpoint usage before the store closes
        self.filer.close()
        self._pool.shutdown(wait=False)

    # -- cluster helpers ---------------------------------------------------

    def assign(self, count: int = 1, collection: str = "",
               replication: str = "", ttl_sec: int = 0,
               data_center: str = "", rack: str = "") -> AssignResult:
        ttl = f"{max(1, ttl_sec // 60)}m" if ttl_sec else ""
        return assign_any(
            self._master_order(),
            count=count,
            collection=collection,
            replication=replication or self.default_replication,
            ttl=ttl,
            data_center=data_center,
            rack=rack,
        )

    def _master_order(self) -> list[str]:
        cur = self.master_client.current_master
        if cur:
            return [cur, *[m for m in self.masters if m != cur]]
        return list(self.masters)

    def _delete_chunks(self, file_ids: list[str]) -> None:
        delete_file_ids(self.master_client.lookup_volume, file_ids)

    # -- write path --------------------------------------------------------

    def write_file(self, path: str, data: bytes, mime: str = "",
                   collection: str = "", replication: str = "",
                   ttl: str = "",
                   signatures: list[int] | None = None,
                   extended: dict | None = None) -> filer_pb2.Entry:
        """Auto-chunking upload: split, assign+upload each chunk, CreateEntry."""
        directory, name = split_path(path)
        # quota pre-check BEFORE the chunk uploads: create_entry re-runs
        # the authoritative gate, but failing here keeps an over-quota
        # write from parking orphan chunks on the volume servers first
        self._precheck_quota(directory, name, len(data))
        collection, replication, ttl = self.apply_path_conf(
            path, collection, replication, ttl)
        chunk_size = self.max_mb << 20
        ttl_sec = _ttl_seconds(ttl)
        chunks = []
        offsets = range(0, max(len(data), 1), chunk_size)
        upload_one = lambda off: self._upload_chunk(  # noqa: E731
            data[off : off + chunk_size], off, name, mime,
            collection, replication, ttl,
        )
        if len(offsets) > 1:
            # wrap_context: the pool workers must upload under THIS
            # request's trace, not as orphan roots
            chunks = list(self._pool.map(trace.wrap_context(upload_one),
                                         offsets))
        elif data:
            chunks = [upload_one(0)]
        entry = filer_pb2.Entry(name=name)
        for k, v in (extended or {}).items():
            # caller-supplied extended attrs (the geo applier passes the
            # ORIGIN's HLC stamp through here so LWW compares origin
            # write time, not relay time)
            entry.extended[k] = v
        entry.chunks.extend(self.manifestize_chunks(chunks, path=path))
        entry.attributes.file_size = len(data)
        entry.attributes.mime = mime
        entry.attributes.mtime = int(time.time())
        entry.attributes.crtime = int(time.time())
        entry.attributes.file_mode = 0o644
        entry.attributes.collection = collection
        entry.attributes.replication = replication
        entry.attributes.ttl_sec = ttl_sec
        self.filer.create_entry(directory, entry, signatures=signatures)
        return entry

    def apply_path_conf(self, path: str, collection: str,
                        replication: str, ttl: str) -> tuple[str, str, str]:
        """Fill unset storage knobs from the matching filer.conf rule.

        /etc/ is exempt: the conf file itself (and the IAM identity
        json) must never land on a TTL'd or deletable-collection volume
        a broad rule selects — that would self-destruct the config."""
        if path.startswith("/etc/"):
            return collection, replication, ttl
        if collection and replication and ttl:
            return collection, replication, ttl
        rule = self.filer_conf.match(path)
        if rule is None:
            return collection, replication, ttl
        return (collection or rule.get("collection", ""),
                replication or rule.get("replication", ""),
                ttl or rule.get("ttl", ""))

    def _upload_chunk(self, blob: bytes, offset: int, name: str, mime: str,
                      collection: str, replication: str, ttl: str
                      ) -> filer_pb2.FileChunk:
        """Assign + upload one chunk.  When the assigned volume server
        cannot take the write even after upload_data's own retries, the
        chunk is RE-ASSIGNED — the master hands out a different target
        and the stale fid is abandoned (it was never recorded anywhere,
        so it costs nothing)."""
        from ..util.cipher import maybe_seal

        from ..operation.upload import VolumeFullError

        stored, cipher_key = maybe_seal(blob, self.cipher)
        last: Exception | None = None
        round_no, rounds = 0, 3
        while round_no < rounds:
            round_no += 1
            result = assign_any(
                self._master_order(), count=1, collection=collection,
                replication=replication or self.default_replication, ttl=ttl,
            )
            try:
                up = upload_data(
                    result.fid_url(), stored, filename=name, mime=mime,
                    jwt=result.auth,
                )
            except Exception as e:  # noqa: BLE001 - re-assign and retry
                last = e
                reason = "reassign"
                if isinstance(e, VolumeFullError):
                    # typed 409: the target disk is full.  The volume
                    # server forced a heartbeat, so the master excludes
                    # it within ~one pulse — a short pause + extra
                    # rounds beats failing a healthy cluster's write
                    # during that propagation window
                    reason = "volume_full"
                    rounds = max(rounds, 6)
                    time.sleep(0.2)
                failsafe.RETRY_COUNTER.labels(
                    "filer", "upload_chunk", reason).inc()
                glog.warning(
                    "chunk upload to %s failed (%s); re-assigning trace=%s",
                    result.url, e, trace.current_trace_id() or "-")
                continue
            chunk = filechunks.make_chunk(
                result.fid, offset, len(blob), time.time_ns(), e_tag=up.etag
            )
            chunk.cipher_key = cipher_key
            return chunk
        raise IOError(f"chunk upload failed after re-assigns: {last}")

    def _precheck_quota(self, directory: str, name: str,
                        new_bytes: int, append: bool = False) -> None:
        from .filer import _entry_bytes
        from .fleet.tenant import tenant_for_path

        tenant = tenant_for_path(f"{directory}/{name}")
        if not tenant:
            return
        old = self.filer.store.find_entry(directory, name)
        old_is_file = old is not None and not old.is_directory
        d_bytes = new_bytes if append else (
            new_bytes - (_entry_bytes(old) if old_is_file else 0))
        self.tenants.check_quota(
            tenant, 0 if old_is_file else 1, d_bytes)

    def append_file(self, path: str, data: bytes, mime: str = "",
                    collection: str = "", replication: str = "",
                    ttl: str = "") -> filer_pb2.Entry:
        """Append bytes as a new chunk (AppendToEntry semantics over HTTP;
        used by log-style writers like the message broker)."""
        directory, name = split_path(path)
        self._precheck_quota(directory, name, len(data), append=True)
        collection, replication, ttl = self.apply_path_conf(
            path, collection, replication, ttl)
        chunk = self._upload_chunk(
            data, 0, name, mime, collection or self.filer.bucket_collection(path),
            replication, ttl,
        )
        self.filer.append_chunks(directory, name, [chunk])
        return self.filer.store.find_entry(directory, name)

    # -- read path ---------------------------------------------------------

    def read_entry_range(self, entry: filer_pb2.Entry, offset: int,
                         size: int) -> bytes:
        if entry.content:  # inline small-file content
            return bytes(entry.content[offset : offset + size])
        chunks = self.resolve_chunks(list(entry.chunks))
        views = filechunks.view_from_chunks(chunks, offset, size)
        if not views:
            return b""
        if len(views) == 1:
            return self._fetch_view(views[0])
        parts = list(self._pool.map(trace.wrap_context(self._fetch_view),
                                    views))
        # assemble honoring logical offsets (holes read as zeros)
        out = bytearray(size)
        for v, blob in zip(views, parts):
            lo = v.logical_offset - offset
            out[lo : lo + len(blob)] = blob
        return bytes(out)

    def resolve_chunks(self, chunks: list) -> list:
        """Expand manifest chunks (cached) into the real chunk list."""
        if not filechunk_manifest.has_chunk_manifest(chunks):
            return chunks
        return filechunk_manifest.resolve_chunk_manifest(
            self._fetch_whole, chunks
        )

    def _download_failover(self, file_id: str,
                           range_header: str | None = None) -> bytes:
        """Fetch chunk bytes with replica failover + EC degraded-read
        fallback.

        Round 0 walks the cached locations (breaker-gated, connection-
        refused locations evicted from the vid cache).  Round 1 forces a
        fresh master lookup — after a volume moved, lost its last live
        replica, or was EC-encoded, the master's answer names the servers
        that can still produce the bytes (EC shard holders rebuild the
        needle on the fly), so a 5xx only surfaces once even the rebuilt
        path is gone."""
        vid = int(file_id.split(",", 1)[0])

        def urls_for(round_no: int) -> list[str]:
            return self.master_client.lookup_file_id(
                file_id, refresh=round_no > 0)

        def fetch(url: str) -> bytes:
            faultpoint.inject(FP_CHUNK_FETCH, ctx=url)
            # single attempt per location: rotation IS the retry here
            return download(url, range_header=range_header, retries=1,
                            use_breaker=False)

        def on_failure(url: str, exc: BaseException) -> None:
            if failsafe.is_connection_refused(exc):
                self.master_client.invalidate_location(vid, url)

        try:
            with failsafe.deadline_scope(CHUNK_READ_DEADLINE_S):
                return failsafe.call_with_failover(
                    urls_for, fetch, op="chunk_read", retry_type="filer",
                    policy=failsafe.RetryPolicy(
                        max_attempts=2, base_delay=0.05, max_delay=0.5),
                    idempotent=True, on_peer_failure=on_failure,
                    peer_key=_netloc,
                )
        except failsafe.CircuitOpenError:
            raise IOError(f"no locations for chunk {file_id}")
        except Exception as e:
            raise IOError(f"chunk {file_id} unreadable: {e}") from e

    def _fetch_whole(self, file_id: str) -> bytes:
        """Whole-chunk fetch through the tiered cache."""
        cached = self.chunk_cache.get(file_id)
        if cached is not None:
            return cached
        blob = self._download_failover(file_id)
        self.chunk_cache.set(file_id, blob)
        return blob

    def _fetch_view(self, view: filechunks.ChunkView) -> bytes:
        if view.cipher_key:
            # GCM cannot be ranged: fetch the whole stored blob (cached
            # as ciphertext), decrypt, then slice the logical view
            from ..util.cipher import decrypt

            blob = decrypt(self._fetch_whole(view.file_id),
                           bytes(view.cipher_key))
            return blob[view.offset : view.offset + view.size]
        cached = self.chunk_cache.get(view.file_id)
        if cached is not None:
            return cached[view.offset : view.offset + view.size]
        # small chunks: fetch whole + cache; large: ranged read, no cache
        if view.chunk_size and view.chunk_size <= (self.max_mb << 20):
            blob = self._fetch_whole(view.file_id)
            return blob[view.offset : view.offset + view.size]
        rng = f"bytes={view.offset}-{view.offset + view.size - 1}"
        return self._download_failover(view.file_id, range_header=rng)

    def manifestize_chunks(self, chunks: list, path: str = "") -> list:
        """Fold an over-long chunk list into manifest chunks before the
        entry hits the metadata store (filer_grpc_server.go MaybeManifestize
        on create/update)."""

        def save(blob: bytes) -> filer_pb2.FileChunk:
            result = assign_any(
                self._master_order(), count=1,
                collection=self.filer.bucket_collection(path),
                replication=self.default_replication,
            )
            upload_data(result.fid_url(), blob, jwt=result.auth)
            return filechunks.make_chunk(result.fid, 0, len(blob),
                                         time.time_ns())

        return filechunk_manifest.maybe_manifestize(
            save, chunks, self.manifest_batch
        )

    # -- collections / brokers --------------------------------------------

    def delete_collection(self, collection: str) -> None:
        from ..pb import master_pb2

        self.filer.delete_collection_entries(collection)
        for m in self._master_order():
            try:
                rpclib.master_stub(m, timeout=30).CollectionDelete(
                    master_pb2.CollectionDeleteRequest(name=collection)
                )
                return
            except Exception:
                continue

    def register_broker(self, resource: str, grpc_address: str) -> None:
        self._brokers.setdefault(resource, [])
        if grpc_address not in self._brokers[resource]:
            self._brokers[resource].append(grpc_address)

    def locate_broker(self, resource: str) -> filer_pb2.LocateBrokerResponse:
        resp = filer_pb2.LocateBrokerResponse(found=resource in self._brokers)
        for addr in self._brokers.get(resource, ()):
            resp.resources.add(grpc_addresses=addr, resource_count=1)
        return resp


def _ttl_seconds(ttl: str) -> int:
    if not ttl:
        return 0
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400, "w": 604800}
    try:
        if ttl[-1] in units:
            return int(ttl[:-1]) * units[ttl[-1]]
        return int(ttl)
    except ValueError:
        return 0
