"""Chunked-file interval model.

Reference: weed/filer/filechunks.go — a file is an ordered list of
FileChunk(fid, offset, size, mtime); later-written chunks shadow earlier
ones where they overlap, so reads resolve the chunk list into a sequence of
visible intervals, and compaction drops fully-shadowed chunks.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..pb import filer_pb2


def total_size(chunks) -> int:
    """Logical file size = max chunk extent (filechunks.go TotalSize)."""
    size = 0
    for c in chunks:
        size = max(size, c.offset + c.size)
    return size


def etag(chunks) -> str:
    """Weak etag over the chunk etags (filechunks.go ETag)."""
    if len(chunks) == 1:
        return chunks[0].e_tag
    import hashlib

    h = hashlib.md5()
    for c in chunks:
        h.update(c.e_tag.encode())
    return h.hexdigest()


@dataclass
class VisibleInterval:
    start: int
    stop: int
    file_id: str
    mtime: int
    chunk_offset: int  # offset of `start` within the chunk's data
    chunk_size: int
    cipher_key: bytes = b""
    is_compressed: bool = False


@dataclass
class ChunkView:
    file_id: str
    offset: int  # offset within the chunk's blob
    size: int
    logical_offset: int  # position in the file
    chunk_size: int
    cipher_key: bytes = b""
    is_compressed: bool = False


def non_overlapping_visible_intervals(chunks) -> list[VisibleInterval]:
    """Resolve the chunk list into disjoint visible intervals.

    Chunks are applied in (mtime, fid) order; each newer chunk punches its
    range out of the accumulated older intervals (filechunks.go
    NonOverlappingVisibleIntervals / MergeIntoVisibles).
    """
    ordered = sorted(chunks, key=lambda c: (c.mtime, c.file_id))
    visibles: list[VisibleInterval] = []
    for c in ordered:
        new = VisibleInterval(
            start=c.offset,
            stop=c.offset + c.size,
            file_id=c.file_id,
            mtime=c.mtime,
            chunk_offset=0,
            chunk_size=c.size,
            cipher_key=bytes(c.cipher_key),
            is_compressed=c.is_compressed,
        )
        out: list[VisibleInterval] = []
        for v in visibles:
            if v.stop <= new.start or v.start >= new.stop:
                out.append(v)  # disjoint
                continue
            if v.start < new.start:  # left remainder survives
                out.append(
                    VisibleInterval(
                        v.start, new.start, v.file_id, v.mtime,
                        v.chunk_offset, v.chunk_size, v.cipher_key,
                        v.is_compressed,
                    )
                )
            if v.stop > new.stop:  # right remainder survives
                out.append(
                    VisibleInterval(
                        new.stop, v.stop, v.file_id, v.mtime,
                        v.chunk_offset + (new.stop - v.start), v.chunk_size,
                        v.cipher_key, v.is_compressed,
                    )
                )
        out.append(new)
        out.sort(key=lambda v: v.start)
        visibles = out
    return visibles


def view_from_visibles(
    visibles: list[VisibleInterval], offset: int, size: int
) -> list[ChunkView]:
    """Chunk views covering [offset, offset+size) (filechunks.go ViewFromVisibleIntervals)."""
    stop = offset + size
    views: list[ChunkView] = []
    for v in visibles:
        lo = max(v.start, offset)
        hi = min(v.stop, stop)
        if lo >= hi:
            continue
        views.append(
            ChunkView(
                file_id=v.file_id,
                offset=v.chunk_offset + (lo - v.start),
                size=hi - lo,
                logical_offset=lo,
                chunk_size=v.chunk_size,
                cipher_key=v.cipher_key,
                is_compressed=v.is_compressed,
            )
        )
    return views


def view_from_chunks(chunks, offset: int, size: int) -> list[ChunkView]:
    return view_from_visibles(
        non_overlapping_visible_intervals(chunks), offset, size
    )


def compact_chunks(chunks) -> tuple[list, list]:
    """-> (compacted, garbage): drop chunks fully shadowed by newer writes
    (filechunks.go CompactFileChunks)."""
    visible_fids = {v.file_id for v in non_overlapping_visible_intervals(chunks)}
    compacted, garbage = [], []
    for c in chunks:
        (compacted if c.file_id in visible_fids else garbage).append(c)
    return compacted, garbage


def minus_chunks(older, newer) -> list:
    """Chunks in `older` not present in `newer` (by fid) — the delta whose
    blobs must be deleted after an entry update (filechunks.go MinusChunks)."""
    keep = {c.file_id for c in newer}
    return [c for c in older if c.file_id not in keep]


def make_chunk(file_id: str, offset: int, size: int, mtime: int,
               e_tag: str = "", is_compressed: bool = False) -> filer_pb2.FileChunk:
    return filer_pb2.FileChunk(
        file_id=file_id,
        offset=offset,
        size=size,
        mtime=mtime,
        e_tag=e_tag,
        is_compressed=is_compressed,
    )
