"""Filer: the path -> chunks metadata plane.

Reference surface: weed/filer (filer.go:30, filerstore.go:18-41,
filechunks.go) + weed/server/filer_server*.go.
"""

from .filer import Filer
from .filerstore import FilerStore

__all__ = ["Filer", "FilerStore"]
