"""FleetFilerClient: the S3 gateway's filer surface, ring-routed.

Drop-in for ``s3api.filer_client.FilerClient`` — same method surface —
but every operation routes through the consistent-hash ring to the
shard that owns its path, with deterministic failover to ring
successors when the owner is unreachable.  Cross-shard listings (the
``/buckets`` directory itself, and ``/``) fan out to every live shard
and merge, so a freshly created bucket is visible before peer
replication catches up on the other shards.

Failover only triggers on TRANSPORT failures (connection refused, gRPC
UNAVAILABLE, a broken stream): an HTTP error status is a real answer
from a live shard — in particular a 503 SlowDown from admission control
must surface to the client, not silently shop the request to a
less-loaded shard and defeat the throttle.
"""

from __future__ import annotations

import threading
import urllib.error

import grpc

from ...pb import filer_pb2
from ...s3api.filer_client import FilerClient, FilerUnavailable
from ...util.executors import MeteredThreadPoolExecutor
from .ring import shard_key
from .router import FleetRouter

# distinct shards tried per operation before giving up
MAX_TRIES = 3

_FAILOVER_GRPC = (grpc.StatusCode.UNAVAILABLE,)


def _is_transport_failure(e: BaseException) -> bool:
    if isinstance(e, FilerUnavailable):
        return True
    if isinstance(e, grpc.RpcError):
        code = e.code() if callable(getattr(e, "code", None)) else None
        return code in _FAILOVER_GRPC
    if isinstance(e, urllib.error.HTTPError):
        return False  # a real answer from a live shard
    if isinstance(e, urllib.error.URLError):
        return True
    return isinstance(e, (ConnectionError, TimeoutError))


class FleetFilerClient:
    def __init__(self, router: FleetRouter):
        self.router = router
        self._clients: dict[str, FilerClient] = {}
        self._clients_lock = threading.Lock()
        # cross-shard listings fan out CONCURRENTLY: latency is bounded
        # by the slowest shard, not the sum over the fleet (saturation
        # visible as seaweedfs_executor_*{executor="fleet_fanout"})
        self._fanout_pool = MeteredThreadPoolExecutor(
            max_workers=8, name="fleet_fanout")

    @property
    def http_address(self) -> str:
        try:
            ring = self.router.ring()
        except Exception:  # noqa: BLE001 — a log label, never fatal
            return "fleet[?]"
        return f"fleet[{len(ring)}]@{ring.version()}"

    def _client(self, addr: str) -> FilerClient:
        with self._clients_lock:
            c = self._clients.get(addr)
            if c is None:
                c = self._clients[addr] = FilerClient(addr)
            return c

    # -- routing core ------------------------------------------------------

    def _run(self, path: str, fn):
        """fn(FilerClient) on the owner of ``path``, failing over in
        ring order; a transport failure forces a membership refresh so
        the second round routes on a post-mortem ring.  When the WHOLE
        local cluster is gone (empty ring or every shard dark) and a
        geo fallback is configured, the operation fails over to the
        remote cluster — a gateway survives its local cluster dying."""
        tried: set[str] = set()
        last: BaseException | None = None
        outage = False  # no usable local membership at all
        candidates: list[str] = []
        geo = self.router.remote is not None
        # with a geo fallback configured, total local loss must be
        # PROVEN before dodging to the remote cluster: sweep EVERY
        # local shard instead of stopping at the bounded-latency try
        # cap (a capped sweep over a >MAX_TRIES fleet would classify
        # an all-dark cluster as a partial outage and 503 forever)
        local_cap = None if geo else MAX_TRIES
        for _round in range(2):
            try:
                candidates = self.router.candidates(path)
            except (LookupError, OSError) as e:
                # empty ring (LookupError: master up, zero live
                # registrations) or discovery failure (IOError: no
                # master answered, no cached ring): no local membership
                # either way — an outage; try the geo fallback before
                # surfacing.  Anything else (a routing BUG) propagates:
                # masking it as an outage would silently shift all
                # traffic to the remote cluster
                last = last or e
                outage = True
                break
            for addr in candidates:
                if addr in tried:
                    continue
                if local_cap is not None and len(tried) >= local_cap:
                    break
                tried.add(addr)
                try:
                    result = fn(self._client(addr))
                except BaseException as e:  # noqa: BLE001 — classified
                    if not _is_transport_failure(e):
                        raise
                    last = e
                    self.router.note_failure(addr)
                    continue
                self.router.note_route(
                    "ok" if len(tried) == 1 else "failover")
                return result
        if not outage and any(a not in tried for a in candidates):
            # only reachable WITHOUT a geo fallback: the try cap
            # stopped the sweep with live-listed shards still untried —
            # a partial outage; surface the retryable 503
            self.router.note_route("error")
            raise FilerUnavailable(
                f"no filer shard reachable for {path!r} within "
                f"{MAX_TRIES} tries ({sorted(tried)}): {last}")
        remote_tried = 0
        for addr in self.router.remote_candidates(path):
            if addr in tried:
                continue
            if remote_tried >= MAX_TRIES:
                break
            tried.add(addr)
            remote_tried += 1
            try:
                result = fn(self._client(addr))
            except BaseException as e:  # noqa: BLE001 — classified
                if not _is_transport_failure(e):
                    raise
                last = e
                continue
            self.router.note_route("remote")
            return result
        self.router.note_route("error")
        if outage and not tried:
            raise FilerUnavailable(f"no local filer membership: {last}")
        raise FilerUnavailable(
            f"no filer shard reachable for {path!r} "
            f"(tried {sorted(tried)}): {last}")

    def _fanout_shards(self) -> list[str]:
        nodes = list(self.router.ring().nodes)
        if not nodes:
            raise FilerUnavailable("filer ring is empty")
        return nodes

    # -- metadata ----------------------------------------------------------

    def find_entry(self, directory: str,
                   name: str) -> filer_pb2.Entry | None:
        path = f"{directory.rstrip('/')}/{name}"
        return self._run(path, lambda c: c.find_entry(directory, name))

    def list_entries(self, directory: str, prefix: str = "",
                     start_from: str = "", inclusive: bool = False,
                     limit: int = 1024) -> list[filer_pb2.Entry]:
        if shard_key(directory) != "/":
            return self._run(
                directory,
                lambda c: c.list_entries(directory, prefix=prefix,
                                         start_from=start_from,
                                         inclusive=inclusive, limit=limit))
        # cross-shard directory (/, /buckets): merge every live shard's
        # answer, fetched concurrently.  Replication makes the lists
        # converge; the merge keeps the window between a create and its
        # replay invisible.
        merged: dict[str, filer_pb2.Entry] = {}
        reached = 0
        last: BaseException | None = None

        def list_one(addr: str):
            return self._client(addr).list_entries(
                directory, prefix=prefix, start_from=start_from,
                inclusive=inclusive, limit=limit)

        futures = [(addr, self._fanout_pool.submit(list_one, addr))
                   for addr in self._fanout_shards()]
        for addr, fut in futures:
            try:
                batch = fut.result()
            except BaseException as e:  # noqa: BLE001
                if not _is_transport_failure(e):
                    raise
                last = e
                self.router.note_failure(addr)
                continue
            reached += 1
            for entry in batch:
                merged.setdefault(entry.name, entry)
        if not reached:
            self.router.note_route("error")
            raise FilerUnavailable(
                f"no filer shard reachable for listing {directory!r}: "
                f"{last}")
        self.router.note_route("ok")
        return [merged[name] for name in sorted(merged)][:limit]

    def iter_entries(self, directory: str, prefix: str = "",
                     page: int = 1024):
        start, inclusive = "", False
        while True:
            batch = self.list_entries(directory, prefix=prefix,
                                      start_from=start, inclusive=inclusive,
                                      limit=page)
            yield from batch
            if len(batch) < page:
                return
            start, inclusive = batch[-1].name, False

    def walk(self, directory: str):
        from collections import deque

        queue = deque([directory.rstrip("/") or "/"])
        while queue:
            d = queue.popleft()
            for entry in self.iter_entries(d):
                yield d, entry
                if entry.is_directory:
                    queue.append((d.rstrip("/") or "") + "/" + entry.name)

    def create_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        path = f"{directory.rstrip('/')}/{entry.name}"
        self._run(path, lambda c: c.create_entry(directory, entry))

    def update_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        path = f"{directory.rstrip('/')}/{entry.name}"
        self._run(path, lambda c: c.update_entry(directory, entry))

    def mkdir(self, directory: str, name: str, mode: int = 0o777) -> None:
        path = f"{directory.rstrip('/')}/{name}"
        self._run(path, lambda c: c.mkdir(directory, name, mode))

    def delete_entry(self, directory: str, name: str,
                     is_delete_data: bool = True,
                     is_recursive: bool = False) -> str:
        path = f"{directory.rstrip('/')}/{name}"
        return self._run(
            path, lambda c: c.delete_entry(
                directory, name, is_delete_data=is_delete_data,
                is_recursive=is_recursive))

    # -- bytes -------------------------------------------------------------

    def put_object(self, path: str, data: bytes, mime: str = "") -> None:
        self._run(path, lambda c: c.put_object(path, data, mime=mime))

    # streamed PUTs up to this size buffer into memory so they can fail
    # over between shards like every other write; larger bodies stream
    # to the owner only (a half-consumed reader cannot be replayed)
    STREAM_FAILOVER_MAX = 8 << 20

    def put_object_stream(self, path: str, reader, length: int,
                          mime: str = "") -> None:
        if length <= self.STREAM_FAILOVER_MAX:
            chunks: list[bytes] = []
            got = 0
            while got < length:
                b = reader.read(min(1 << 20, length - got))
                if not b:
                    # a short body must fail the upload, never commit a
                    # truncated object (the non-fleet path fails at the
                    # transport when Content-Length goes unmet)
                    raise IOError(
                        f"short object body: got {got} of {length} bytes")
                chunks.append(b)
                got += len(b)
            return self.put_object(path, b"".join(chunks), mime=mime)
        try:
            addr = self.router.owner(path)
        except LookupError as e:
            raise FilerUnavailable(f"filer ring is empty: {e}")
        try:
            self._client(addr).put_object_stream(path, reader, length,
                                                 mime=mime)
        except BaseException as e:  # noqa: BLE001
            if _is_transport_failure(e):
                self.router.note_failure(addr)
                self.router.note_route("error")
            raise
        self.router.note_route("ok")

    def open_object(self, path: str, range_header: str = ""):
        return self._run(
            path, lambda c: c.open_object(path, range_header=range_header))

    def get_object(self, path: str,
                   range_header: str = "") -> tuple[int, dict, bytes]:
        return self._run(
            path, lambda c: c.get_object(path, range_header=range_header))
