"""Filer fleet: the sharded metadata plane (ISSUE 7).

One filer process fronting one store caps directory-listing and
small-object QPS no matter how fast the data plane is.  The fleet splits
that plane three ways:

* ``ring``     — a consistent-hash ring (virtual nodes) that shards the
  namespace by bucket / top-level prefix across N filer instances, each
  owning its own ``FilerStore``;
* ``router``   — gateway-side membership discovery (the master's filer
  registrations from PR 5's KeepConnected plane) + ring construction, so
  gateways stay stateless: every routing decision derives from the
  master-discovered snapshot;
* ``tenant``   — per-tenant namespaces with quotas (object count +
  bytes, enforced where the shard owner runs) and weighted-fair-queueing
  admission control on the filer serving executors, driven by the PR 5
  queue-depth gauges.

Durability under shard death comes from the existing metadata federation
(``filer/meta_aggregator.py``): fleet filers peer with each other, every
mutation replays into every peer's store, so when a shard dies the ring
re-routes its keys to the successor — which already holds the namespace.
The ring decides *ownership* (who serves and accounts for a prefix); the
aggregator decides *survival*.
"""

from .ring import HashRing, shard_key
from .router import FleetRouter
from .tenant import (
    AdmissionController,
    QuotaExceededError,
    SlowDownError,
    TenantManager,
    tenant_for_path,
)

__all__ = [
    "AdmissionController",
    "FleetFilerClient",
    "FleetRouter",
    "HashRing",
    "QuotaExceededError",
    "SlowDownError",
    "TenantManager",
    "shard_key",
    "tenant_for_path",
]


def __getattr__(name: str):
    # FleetFilerClient wraps the S3 gateway's FilerClient, and s3api in
    # turn imports this package's tenant errors — loading it lazily
    # keeps the package import acyclic
    if name == "FleetFilerClient":
        from .fleet_client import FleetFilerClient

        return FleetFilerClient
    raise AttributeError(name)
