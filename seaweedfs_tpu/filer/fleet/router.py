"""Gateway-side fleet routing: master-discovered membership -> ring.

The gateways hold NO durable routing state.  Membership comes from the
master's observability plane — filers register over KeepConnected with
``client_type="filer"`` and a scrapeable HTTP address (PR 5), and
``GET /cluster/status`` serves them with per-client liveness.  The
router polls that, filters stale registrations, and rebuilds the ring
whenever membership changes; a routing failure forces an immediate
refresh so a SIGKILLed filer stops being the owner within one
round-trip of the master noticing, not a cache TTL later.

A restarted gateway reconstructs the identical ring from the same
master answer — that is the statelessness contract the acceptance test
pins (restart a gateway mid-test; behavior identical).
"""

from __future__ import annotations

import json
import threading
import time

from ...stats.metrics import RING_NODES, RING_REFRESH, RING_ROUTE
from ...util import connpool, faultpoint, glog
from .ring import DEFAULT_VNODES, HashRing, shard_key

# how long a discovered membership snapshot is trusted before re-asking
# the master; routing failures bypass the TTL
MEMBERSHIP_TTL_S = 2.0

# a filer whose KeepConnected registration went quiet for this long is
# dropped from the ring even if the master still lists it
STALE_FILER_S = 30.0

FP_RING_ROUTE = faultpoint.register("filer.ring.route")


class FleetRouter:
    """Membership discovery + ring construction for one gateway process.

    Two modes:
    * static   — ``filers=[...]`` pins the membership (tests, fixed
      fleets without a master);
    * discover — ``masters=[...]`` (HTTP addresses) polls
      /cluster/status for live filer registrations.
    """

    def __init__(self, masters: list[str] | None = None,
                 filers: list[str] | None = None,
                 vnodes: int = DEFAULT_VNODES,
                 membership_ttl_s: float = MEMBERSHIP_TTL_S,
                 remote_masters: list[str] | None = None,
                 remote_filers: list[str] | None = None):
        self.masters = [m.strip() for m in (masters or []) if m.strip()]
        self.static_filers = [f.strip() for f in (filers or []) if f.strip()]
        if not self.masters and not self.static_filers:
            raise ValueError("FleetRouter needs masters or a filer list")
        self.vnodes = vnodes
        self.membership_ttl_s = membership_ttl_s
        self._lock = threading.Lock()
        self._ring = HashRing(self.static_filers, vnodes)
        self._fetched_at = time.monotonic() if self.static_filers else 0.0
        if self.static_filers:
            RING_NODES.labels().set(len(self.static_filers))
        # geo failover (ISSUE 12): a second, REMOTE-cluster ring the
        # fleet client falls back to when every local shard is gone —
        # read-from-nearest (local cluster first, always), fail over to
        # the remote cluster only on total local loss.  Active-active
        # replication makes remote writes safe: they ship back once the
        # local cluster rejoins.
        self.remote: FleetRouter | None = None
        if remote_masters or remote_filers:
            self.remote = FleetRouter(
                masters=remote_masters, filers=remote_filers,
                vnodes=vnodes, membership_ttl_s=membership_ttl_s)

    # -- membership --------------------------------------------------------

    def _discover(self) -> list[str]:
        """Live filer HTTP addresses from the first answering master."""
        last: Exception | None = None
        for master in self.masters:
            try:
                with connpool.request(
                        "GET", f"http://{master}/cluster/status",
                        timeout=5) as r:
                    doc = json.loads(r.read())
            except Exception as e:  # noqa: BLE001 — rotate masters
                last = e
                continue
            filers = []
            for info in (doc.get("Filers") or {}).values():
                addr = info.get("httpAddress")
                age = info.get("secondsSinceLastSeen", 0.0)
                if addr and float(age or 0.0) < STALE_FILER_S:
                    filers.append(addr)
            return sorted(set(filers))
        raise IOError(f"no master answered /cluster/status: {last}")

    def refresh(self, force: bool = False) -> HashRing:
        """Return the current ring, re-discovering membership when the
        snapshot aged out (or ``force``)."""
        if self.static_filers:
            return self._ring
        with self._lock:
            fresh = (time.monotonic() - self._fetched_at
                     < self.membership_ttl_s)
            if fresh and not force and self._ring:
                return self._ring
            try:
                members = self._discover()
            except Exception as e:  # noqa: BLE001 — keep the stale ring
                RING_REFRESH.labels("error").inc()
                if self._ring:
                    glog.warning("filer ring refresh failed (%s); "
                                 "keeping %d-node snapshot", e,
                                 len(self._ring))
                    return self._ring
                raise
            RING_REFRESH.labels("forced" if force else "ttl").inc()
            if members != self._ring.nodes:
                old = self._ring.version() if self._ring else "-"
                self._ring = HashRing(members, self.vnodes)
                glog.info("filer ring %s -> %s members=%s",
                          old, self._ring.version(), members)
            RING_NODES.labels().set(len(members))
            self._fetched_at = time.monotonic()
            return self._ring

    def ring(self) -> HashRing:
        return self.refresh()

    # -- routing -----------------------------------------------------------

    def candidates(self, path: str) -> list[str]:
        """Failover-ordered filer addresses for ``path`` (owner first).

        Cross-shard keys (``shard_key == "/"``) still return a full
        deterministic order — callers that need a fan-out use
        ``ring().nodes`` instead."""
        faultpoint.inject(FP_RING_ROUTE, ctx=path)
        ring = self.refresh()
        return ring.lookup_order(shard_key(path))

    def owner(self, path: str) -> str:
        faultpoint.inject(FP_RING_ROUTE, ctx=path)
        return self.refresh().lookup(shard_key(path))

    def remote_candidates(self, path: str) -> list[str]:
        """Failover-ordered REMOTE-cluster filers for ``path``; empty
        when no geo fallback is configured or the remote cluster is
        undiscoverable (the caller surfaces the local failure then)."""
        if self.remote is None:
            return []
        try:
            return self.remote.candidates(path)
        except Exception as e:  # noqa: BLE001 — both clusters dark
            glog.warning("geo-failover discovery failed: %s", e)
            return []

    def note_route(self, result: str) -> None:
        """result ∈ ok | failover | error (one per routed operation)."""
        RING_ROUTE.labels(result).inc()

    def note_failure(self, addr: str) -> None:
        """A candidate failed at the transport level: force the next
        routing decision to re-ask the master (the filer may be gone)."""
        with self._lock:
            self._fetched_at = 0.0
