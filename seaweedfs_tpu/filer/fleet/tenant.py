"""Per-tenant namespaces: quotas + weighted-fair admission control.

A tenant is a bucket (``/buckets/<b>/...`` -> tenant ``b``) — the same
unit the ring shards on, so a tenant's accounting always runs on the
shard that owns its writes and never needs cross-filer coordination.
Paths outside /buckets (config, topics, debug surfaces) carry no tenant
and are exempt from both quotas and admission.

Quotas (object count + bytes) are enforced in the Filer mutation path
before the store write; usage counters live in memory and checkpoint
into the store's KV space so restarts resume near-accurate.  Replicated
peer mutations (meta_aggregator replays) bypass the Filer path by
design, so each tenant is accounted exactly once fleet-wide: on its
owning shard.

Admission is rejection-based weighted fair queueing on the serving
executors, the scheduling-and-throttling framing of arXiv:2108.02692
applied to the filer front end: while the filer has headroom everyone
is admitted; once saturated (concurrent admitted requests at capacity,
or the PR 5 ``seaweedfs_executor_queue_depth{executor="filer_chunk"}``
gauge shows the chunk fan-out pool backed up) each tenant is clamped to
its weight's share of capacity.  A saturating tenant gets ``503
SlowDown`` (proper S3 semantics, with Retry-After); a light tenant's
requests keep flowing because its share is reserved, which is the SLO
isolation the fleet acceptance test asserts.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ...stats.metrics import (
    EXECUTOR_QUEUE_DEPTH,
    TENANT_ADMIT,
    TENANT_INFLIGHT,
    TENANT_USAGE_BYTES,
    TENANT_USAGE_OBJECTS,
)
from ...util import glog

CONF_KEY = b"tenant.conf"
USAGE_KEY = b"tenant.usage"

# concurrent admitted requests before WFQ clamping kicks in
ADMIT_CAPACITY = int(os.environ.get(
    "SEAWEEDFS_TPU_FILER_ADMIT_CAPACITY", "32"))
# filer_chunk executor queue depth that also counts as saturation
ADMIT_QUEUE_THRESHOLD = int(os.environ.get(
    "SEAWEEDFS_TPU_FILER_ADMIT_QUEUE", "64"))
# usage checkpoint throttle (replay-safe: counters are advisory)
USAGE_PERSIST_S = 2.0

RETRY_AFTER_S = 1


def tenant_for_path(path: str) -> str:
    """The owning tenant of a filer path; "" when untenanted."""
    p = "/" + (path or "").strip("/")
    segs = p.lstrip("/").split("/")
    if segs[0] == "buckets" and len(segs) > 1 and segs[1]:
        return segs[1]
    return ""


class QuotaExceededError(Exception):
    """The mutation would push the tenant past its configured quota.

    The message prefix is a wire contract: gRPC entry responses carry it
    in their error string and the S3 gateway maps it back to a 403
    QuotaExceeded, so keep ``quota exceeded`` stable.  Deliberately NOT
    an OSError subclass: failsafe.classify treats unknown OSErrors as
    retryable, and a quota rejection re-sent three times with backoff
    would triple load exactly when the tenant is being throttled (plain
    Exceptions classify non-retryable)."""

    def __init__(self, tenant: str, detail: str):
        super().__init__(f"quota exceeded for tenant {tenant!r}: {detail}")
        self.tenant = tenant


class SlowDownError(Exception):
    """Admission rejected the request: the tenant is over its fair share
    of a saturated filer.  Maps to S3 ``503 SlowDown``."""

    def __init__(self, tenant: str, retry_after: int = RETRY_AFTER_S):
        super().__init__(
            f"tenant {tenant!r} over its fair share; slow down")
        self.tenant = tenant
        self.retry_after = retry_after


class TenantManager:
    """Per-tenant config (quotas, WFQ weight) + usage accounting.

    Config and usage checkpoints persist in the filer store's KV space,
    so they shard — and fail over — with the namespace they govern."""

    def __init__(self, store=None):
        self.store = store
        self._lock = threading.Lock()
        self._conf: dict[str, dict] = {}
        self._usage: dict[str, dict[str, int]] = {}
        self._last_persist = time.monotonic()
        if store is not None:
            self._load()

    # -- persistence -------------------------------------------------------

    def _load(self) -> None:
        for key, target in ((CONF_KEY, "_conf"), (USAGE_KEY, "_usage")):
            try:
                raw = self.store.kv_get(key)
                if raw:
                    setattr(self, target, json.loads(raw))
            except Exception as e:  # noqa: BLE001 — never block filer boot
                glog.warning("tenant %s load failed: %s", key, e)
        with self._lock:
            for tenant, u in self._usage.items():
                self._export_usage(tenant, u)

    def _persist_usage(self, force: bool = False) -> None:
        """Caller holds self._lock."""
        if self.store is None:
            return
        now = time.monotonic()
        if not force and now - self._last_persist < USAGE_PERSIST_S:
            return
        self._last_persist = now
        try:
            self.store.kv_put(USAGE_KEY, json.dumps(self._usage).encode())
        except Exception as e:  # noqa: BLE001 — advisory counters
            glog.warning("tenant usage persist failed: %s", e)

    def close(self) -> None:
        with self._lock:
            self._persist_usage(force=True)

    # -- config ------------------------------------------------------------

    def set_config(self, tenant: str, quota_bytes: int | None = None,
                   quota_objects: int | None = None,
                   weight: float | None = None) -> dict:
        with self._lock:
            conf = dict(self._conf.get(tenant, {}))
            if quota_bytes is not None:
                conf["quota_bytes"] = int(quota_bytes)
            if quota_objects is not None:
                conf["quota_objects"] = int(quota_objects)
            if weight is not None:
                conf["weight"] = float(weight)
            self._conf[tenant] = conf
            if self.store is not None:
                try:
                    self.store.kv_put(CONF_KEY,
                                      json.dumps(self._conf).encode())
                except Exception as e:  # noqa: BLE001
                    glog.warning("tenant conf persist failed: %s", e)
            return conf

    def config(self, tenant: str) -> dict:
        with self._lock:
            return dict(self._conf.get(tenant, {}))

    def weight(self, tenant: str) -> float:
        with self._lock:
            w = self._conf.get(tenant, {}).get("weight", 1.0)
        return max(0.01, float(w))

    # -- usage -------------------------------------------------------------

    def _export_usage(self, tenant: str, u: dict[str, int]) -> None:
        TENANT_USAGE_BYTES.labels(tenant).set(u.get("bytes", 0))
        TENANT_USAGE_OBJECTS.labels(tenant).set(u.get("objects", 0))

    def usage(self, tenant: str) -> dict[str, int]:
        with self._lock:
            u = self._usage.get(tenant, {})
            return {"objects": int(u.get("objects", 0)),
                    "bytes": int(u.get("bytes", 0))}

    def check_quota(self, tenant: str, add_objects: int,
                    add_bytes: int) -> None:
        """Raise QuotaExceededError when the pending mutation would land
        the tenant past either bound.  Deletes (negative deltas) always
        pass — a full tenant must be able to free space."""
        if not tenant or (add_objects <= 0 and add_bytes <= 0):
            return
        with self._lock:
            conf = self._conf.get(tenant)
            if not conf:
                return
            u = self._usage.get(tenant, {})
            qo = int(conf.get("quota_objects", 0))
            qb = int(conf.get("quota_bytes", 0))
            if qo and int(u.get("objects", 0)) + add_objects > qo:
                raise QuotaExceededError(
                    tenant, f"{u.get('objects', 0)} + {add_objects} "
                            f"objects > limit {qo}")
            if qb and int(u.get("bytes", 0)) + add_bytes > qb:
                raise QuotaExceededError(
                    tenant, f"{u.get('bytes', 0)} + {add_bytes} "
                            f"bytes > limit {qb}")

    def record(self, tenant: str, d_objects: int, d_bytes: int) -> None:
        if not tenant or (d_objects == 0 and d_bytes == 0):
            return
        with self._lock:
            u = self._usage.setdefault(tenant, {"objects": 0, "bytes": 0})
            u["objects"] = max(0, int(u.get("objects", 0)) + d_objects)
            u["bytes"] = max(0, int(u.get("bytes", 0)) + d_bytes)
            self._export_usage(tenant, u)
            self._persist_usage()

    def snapshot(self) -> dict:
        """/debug/tenants view: config + usage per known tenant."""
        with self._lock:
            tenants = sorted(set(self._conf) | set(self._usage))
            return {
                t: {
                    "config": dict(self._conf.get(t, {})),
                    "usage": {
                        "objects": int(
                            self._usage.get(t, {}).get("objects", 0)),
                        "bytes": int(
                            self._usage.get(t, {}).get("bytes", 0)),
                    },
                }
                for t in tenants
            }


def _chunk_pool_queue_depth() -> float:
    """The PR 5 saturation signal for the filer's chunk fan-out pool."""
    return EXECUTOR_QUEUE_DEPTH.labels("filer_chunk").value


class AdmissionController:
    """Rejection-based WFQ over concurrent admitted requests.

    ``admit(tenant)`` is a context manager the serving path wraps one
    request in.  Below saturation it is one lock + two increments; at
    saturation a tenant already holding >= its weighted share of
    capacity gets SlowDownError while lighter tenants pass."""

    def __init__(self, manager: TenantManager,
                 capacity: int | None = None,
                 queue_threshold: int | None = None,
                 queue_depth_fn=None):
        self.manager = manager
        self.capacity = capacity if capacity is not None else ADMIT_CAPACITY
        self.queue_threshold = (queue_threshold if queue_threshold is not None
                                else ADMIT_QUEUE_THRESHOLD)
        self._queue_depth = queue_depth_fn or _chunk_pool_queue_depth
        self._lock = threading.Lock()
        self._inflight: dict[str, int] = {}
        self._total = 0

    def _share(self, tenant: str, effective_capacity: int) -> int:
        """This tenant's WFQ share of ``effective_capacity`` among
        currently-active tenants (itself included).  At least 1: weights
        throttle, they never starve."""
        weights = {t: self.manager.weight(t)
                   for t, n in self._inflight.items() if n > 0}
        weights[tenant] = self.manager.weight(tenant)
        total_w = sum(weights.values())
        return max(1, int(effective_capacity * weights[tenant] / total_w))

    def try_enter(self, tenant: str) -> None:
        """Admit or raise SlowDownError.  Untenanted paths are exempt
        (admitted, uncounted): config reads and debug surfaces must
        never be collateral of a tenant storm."""
        if not tenant:
            return
        with self._lock:
            at_capacity = self._total >= self.capacity
            queue_backed_up = self._queue_depth() >= self.queue_threshold
            if at_capacity or queue_backed_up:
                # at capacity, shares split the configured width; when
                # only the downstream queue gauge fired, shares split
                # what is ALREADY in flight — admitting more of anyone
                # just grows the backlog, so the clamp freezes growth
                effective = (self.capacity if at_capacity
                             else max(1, self._total))
                if self._inflight.get(tenant, 0) >= \
                        self._share(tenant, effective):
                    TENANT_ADMIT.labels(tenant, "slowdown").inc()
                    raise SlowDownError(tenant)
            self._inflight[tenant] = self._inflight.get(tenant, 0) + 1
            self._total += 1
        TENANT_INFLIGHT.labels(tenant).inc()
        TENANT_ADMIT.labels(tenant, "ok").inc()

    def leave(self, tenant: str) -> None:
        if not tenant:
            return
        with self._lock:
            n = self._inflight.get(tenant, 0)
            if n <= 1:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = n - 1
            if n > 0:
                self._total -= 1
                TENANT_INFLIGHT.labels(tenant).dec()

    class _Slot:
        __slots__ = ("ctl", "tenant")

        def __init__(self, ctl, tenant):
            self.ctl = ctl
            self.tenant = tenant

        def __enter__(self):
            self.ctl.try_enter(self.tenant)
            return self

        def __exit__(self, *exc):
            self.ctl.leave(self.tenant)
            return False

    def admit(self, tenant: str) -> "_Slot":
        return self._Slot(self, tenant)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "queueThreshold": self.queue_threshold,
                "inflight": dict(self._inflight),
                "total": self._total,
            }
