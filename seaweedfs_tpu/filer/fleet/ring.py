"""Consistent-hash ring with virtual nodes.

Reference shape: the classic Karger ring as deployed by every
SeaweedFS-class metadata shard map — each physical node is hashed onto
the ring VNODES times, a key routes to the first vnode clockwise from
its hash, and membership churn of one node out of N remaps only ~K/N
keys (the dead node's arcs), never reshuffling the survivors.

Determinism matters more than speed here: every gateway must compute the
SAME mapping from the same membership list, across processes and hosts,
or two gateways would account one bucket to two shards.  Hashing is
therefore md5 over stable strings (no process-seeded ``hash()``), and
lookup is a bisect over the sorted vnode array — O(log vnodes).
"""

from __future__ import annotations

import bisect
import hashlib

DEFAULT_VNODES = 64


def _hash64(s: str) -> int:
    """Stable 64-bit position on the ring (first 8 md5 bytes)."""
    return int.from_bytes(hashlib.md5(s.encode()).digest()[:8], "big")


def shard_key(path: str) -> str:
    """The routing key a filer path shards on.

    ``/buckets/<b>/...`` shards on the bucket — a bucket's whole subtree
    (objects, multipart staging, markers) lands on ONE shard, so every
    read-after-write inside a bucket is served by the store that took
    the write.  Any other absolute path shards on its top-level segment
    (``/etc/...``, ``/topics/...``), keeping each config/topic family
    together.  ``/`` and ``/buckets`` themselves return ``"/"`` — the
    caller treats that as "cross-shard" (listings fan out and merge)."""
    p = "/" + path.strip("/")
    if p == "/":
        return "/"
    segs = p.lstrip("/").split("/")
    if segs[0] == "buckets":
        if len(segs) == 1:
            return "/"
        return "b/" + segs[1]
    return "t/" + segs[0]


class HashRing:
    """Immutable ring snapshot over a membership list."""

    def __init__(self, nodes: list[str], vnodes: int = DEFAULT_VNODES):
        self.nodes = sorted(set(nodes))
        self.vnodes = vnodes
        points: list[tuple[int, str]] = []
        for node in self.nodes:
            for i in range(vnodes):
                points.append((_hash64(f"{node}#{i}"), node))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._owners = [n for _, n in points]

    def __len__(self) -> int:
        return len(self.nodes)

    def __bool__(self) -> bool:
        return bool(self.nodes)

    def version(self) -> str:
        """Stable fingerprint of the membership (snapshot identity)."""
        return hashlib.md5("|".join(self.nodes).encode()).hexdigest()[:12]

    def lookup(self, key: str) -> str:
        """The owning node for ``key`` (first vnode clockwise)."""
        if not self.nodes:
            raise LookupError("empty filer ring")
        i = bisect.bisect_right(self._hashes, _hash64(key))
        if i == len(self._hashes):
            i = 0
        return self._owners[i]

    def lookup_order(self, key: str) -> list[str]:
        """Owner first, then each DISTINCT successor in ring order — the
        failover sequence when the owner is unreachable.  With full
        metadata replication across the fleet any successor can serve
        the keys; ring order keeps the choice deterministic so every
        gateway fails over to the same node."""
        if not self.nodes:
            raise LookupError("empty filer ring")
        start = bisect.bisect_right(self._hashes, _hash64(key))
        out: list[str] = []
        n = len(self._owners)
        for step in range(n):
            owner = self._owners[(start + step) % n]
            if owner not in out:
                out.append(owner)
                if len(out) == len(self.nodes):
                    break
        return out
