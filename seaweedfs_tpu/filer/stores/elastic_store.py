"""elastic7-class FilerStore over Elasticsearch's REST API.

Reference: weed/filer/elastic/v7/elastic_store.go:37-295 — entries are
JSON documents keyed by md5(fullpath) carrying a ParentId = md5(dir) for
directory listings; KV pairs live in a dedicated ``.seaweedfs_kv_entries``
index.  The reference shards entries into one index per top-level
directory; this build keeps a single ``.seaweedfs_entries`` index (the
FilerStore contract is identical — the sharding is an ES capacity knob).

No elasticsearch client library ships in this image, so the store speaks
the REST API directly (PUT/GET/DELETE ``/{index}/_doc/{id}``,
``_search`` with a ParentId term query + ``search_after`` paging sorted
on ``name.keyword``, ``_delete_by_query``) — the same requests work
against a live ES 7 cluster; tests run them against the in-process
FakeElasticServer (util.fake_elastic).
"""

from __future__ import annotations

import base64
import hashlib
import json
import urllib.error
import urllib.request
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store

INDEX_ENTRIES = ".seaweedfs_entries"
INDEX_KV = ".seaweedfs_kv_entries"


def _md5(s: str) -> str:
    return hashlib.md5(s.encode()).hexdigest()


def _join(directory: str, name: str) -> str:
    return (directory.rstrip("/") or "") + "/" + name


@register_store("elastic7")
class ElasticStore(FilerStore):
    name = "elastic7"

    def __init__(self, servers: str = "http://127.0.0.1:9200",
                 username: str = "", password: str = "",
                 max_page_size: int = 10000, **_):
        self.base = servers.split(",")[0].rstrip("/")
        if not self.base.startswith("http"):
            self.base = "http://" + self.base
        self.max_page_size = max_page_size
        self._auth = None
        if username and password:
            self._auth = "Basic " + base64.b64encode(
                f"{username}:{password}".encode()).decode()

    # -- REST plumbing -----------------------------------------------------

    def _req(self, method: str, path: str, body: dict | None = None) -> dict:
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        if self._auth:
            req.add_header("Authorization", self._auth)
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            payload = e.read()
            if e.code == 404:
                try:
                    return json.loads(payload or b"{}") | {"found": False}
                except ValueError:
                    return {"found": False}
            raise IOError(
                f"elastic {method} {path}: {e.code} {payload[:200]!r}"
            ) from None

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        full = _join(directory, entry.name)
        self._req("PUT", f"/{INDEX_ENTRIES}/_doc/{_md5(full)}", {
            "ParentId": _md5(directory),
            "dir": directory,
            "name": entry.name,
            "meta": base64.b64encode(entry.SerializeToString()).decode(),
        })

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        doc = self._req(
            "GET",
            f"/{INDEX_ENTRIES}/_doc/{_md5(_join(directory, name))}")
        if not doc.get("found"):
            return None
        return filer_pb2.Entry.FromString(
            base64.b64decode(doc["_source"]["meta"]))

    def delete_entry(self, directory: str, name: str) -> None:
        self._req("DELETE",
                  f"/{INDEX_ENTRIES}/_doc/{_md5(_join(directory, name))}")

    def delete_folder_children(self, directory: str) -> None:
        # exact children + every descendant's children in one query
        # (the reference iterates-and-deletes; _delete_by_query is the
        # REST-native form of the same contract)
        prefix = directory.rstrip("/") + "/"
        # dir.keyword: with ES dynamic mapping the bare `dir` field is
        # analyzed text (tokenized on '/'), so un-analyzed term/prefix
        # queries against it match NOTHING on a live cluster — only the
        # .keyword sub-field compares whole values
        self._req("POST", f"/{INDEX_ENTRIES}/_delete_by_query", {
            "query": {"bool": {"should": [
                {"term": {"dir.keyword": directory}},
                {"prefix": {"dir.keyword": prefix}},
            ]}},
        })

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        parent = _md5(directory)
        cursor, op = start_from, ("gte" if inclusive else "gt")
        emitted = 0
        while emitted < limit:
            query: dict = {"bool": {
                "must": [{"term": {"ParentId.keyword": parent}}]}}
            if cursor:
                query["bool"]["filter"] = [
                    {"range": {"name.keyword": {op: cursor}}}]
            size = min(limit - emitted, self.max_page_size)
            hits = self._req("POST", f"/{INDEX_ENTRIES}/_search", {
                "query": query,
                "sort": [{"name.keyword": "asc"}],
                "size": size,
            }).get("hits", {}).get("hits", [])
            if not hits:
                return
            for h in hits:
                src = h["_source"]
                cursor, op = src["name"], "gt"
                if prefix and not src["name"].startswith(prefix):
                    continue
                emitted += 1
                yield filer_pb2.Entry.FromString(
                    base64.b64decode(src["meta"]))
                if emitted >= limit:
                    return
            if len(hits) < size:
                return

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        doc = self._req("GET", f"/{INDEX_KV}/_doc/{_md5(key.decode('latin-1'))}")
        if not doc.get("found"):
            return None
        return base64.b64decode(doc["_source"]["Value"])

    def kv_put(self, key: bytes, value: bytes) -> None:
        kid = _md5(key.decode("latin-1"))
        if value:
            self._req("PUT", f"/{INDEX_KV}/_doc/{kid}", {
                "Value": base64.b64encode(value).decode()})
        else:
            self._req("DELETE", f"/{INDEX_KV}/_doc/{kid}")
