"""Embedded FilerStore backends; importing registers them.

Reference analogue: weed/filer/<backend>/ dirs registered via blank-import
init() (weed/server/filer_server.go:23-36).  This build ships the two
embedded classes: in-memory (tests) and sqlite (the leveldb-class default —
single-file, transactional, ordered listing).
"""

from . import memory_store, sqlite_store  # noqa: F401
