"""Embedded FilerStore backends; importing registers them.

Reference analogue: weed/filer/<backend>/ dirs registered via blank-import
init() (weed/server/filer_server.go:23-36).  This build ships three
embedded classes: in-memory (tests), sqlite (single-file, transactional,
ordered listing — the abstract_sql class), and leveldb (bitcask-style
log+snapshot store covering the reference's embedded-leveldb default).
"""

from . import leveldb_store, memory_store, sqlite_store  # noqa: F401
