"""Embedded FilerStore backends; importing registers them.

Reference analogue: weed/filer/<backend>/ dirs registered via blank-import
init() (weed/server/filer_server.go:23-36).  This build ships four
classes: in-memory (tests), sqlite (single-file, transactional,
ordered listing — the abstract_sql class), leveldb (bitcask-style
log+snapshot store covering the reference's embedded-leveldb default),
and redis (any RESP2 endpoint via the framework's own client).
"""

from . import (  # noqa: F401
    leveldb_store,
    memory_store,
    redis_store,
    sqlite_store,
)
