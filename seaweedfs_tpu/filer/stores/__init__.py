"""FilerStore backends; importing registers them.

Reference analogue: weed/filer/<backend>/ dirs registered via blank-import
init() (weed/server/filer_server.go:23-36).  This build ships 11 kinds:
in-memory (tests), sqlite (single-file, transactional, ordered listing),
leveldb (bitcask-style log+snapshot store covering the reference's
embedded-leveldb default), leveldb2 (the same, md5-partitioned 8 ways),
leveldb3 (adaptive per-bucket partitioning with O(1) bucket drops),
redis (RESP2), etcd (etcd v3 gRPC KV), elastic7 (ES REST), mongodb
(OP_MSG wire), cassandra (CQL v4 native protocol) — each external kind
speaks its wire protocol through a framework-native client with an
in-process fake server as its test double — plus the abstract_sql class
with mysql / postgres kinds (DB-API drivers load lazily; absent drivers
raise a loud ConfigurationError).
"""

from . import (  # noqa: F401
    cassandra_store,
    elastic_store,
    etcd_store,
    leveldb2_store,
    leveldb3_store,
    leveldb_store,
    memory_store,
    mongodb_store,
    redis_store,
    sql_store,
    sqlite_store,
)
