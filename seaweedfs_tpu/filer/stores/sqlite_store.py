"""SQLite FilerStore: the embedded default.

Reference analogue: the abstract_sql family (weed/filer/abstract_sql/,
mysql/, postgres/) — one `filemeta(dirhash, name, directory, meta)` table —
fused with leveldb's role as the zero-dependency default store
(weed/filer/leveldb/).  SQLite gives ordered listing, transactions, and a
single-file footprint from the stdlib.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store

def _glob_escape(s: str) -> str:
    """Escape GLOB metacharacters so path text matches literally."""
    return s.replace("[", "[[]").replace("*", "[*]").replace("?", "[?]")


_SCHEMA = """
CREATE TABLE IF NOT EXISTS filemeta (
    directory TEXT NOT NULL,
    name      TEXT NOT NULL,
    meta      BLOB NOT NULL,
    PRIMARY KEY (directory, name)
);
CREATE TABLE IF NOT EXISTS filer_kv (
    k BLOB PRIMARY KEY,
    v BLOB NOT NULL
);
"""


@register_store("sqlite")
class SqliteStore(FilerStore):
    name = "sqlite"

    def __init__(self, path: str = "filer.db", **_):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(_SCHEMA)
        self._lock = threading.RLock()
        # reads run on their OWN connection + lock: WAL already lets a
        # reader see the last committed snapshot while a writer commits,
        # but one shared connection serialized listings behind insert
        # fsyncs — the exact stall the sharded metadata plane exists to
        # remove.  :memory: has no WAL file to share, so it keeps the
        # single-connection behavior.
        if path == ":memory:":
            self._rconn = self._conn
            self._rlock = self._lock
        else:
            self._rconn = sqlite3.connect(path, check_same_thread=False)
            self._rconn.execute("PRAGMA query_only=ON")
            self._rlock = threading.RLock()

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO filemeta (directory, name, meta) "
                "VALUES (?, ?, ?)",
                (directory, entry.name, entry.SerializeToString()),
            )
            self._conn.commit()

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        with self._rlock:
            row = self._rconn.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?",
                (directory, name),
            ).fetchone()
        if row is None:
            return None
        return filer_pb2.Entry.FromString(row[0])

    def delete_entry(self, directory: str, name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?",
                (directory, name),
            )
            self._conn.commit()

    def delete_folder_children(self, directory: str) -> None:
        prefix = directory.rstrip("/") + "/"
        with self._lock:
            self._conn.execute(
                "DELETE FROM filemeta WHERE directory=? OR directory GLOB ?",
                (directory, _glob_escape(prefix) + "*"),
            )
            self._conn.commit()

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        op = ">=" if inclusive else ">"
        sql = (
            "SELECT meta FROM filemeta WHERE directory=? AND name "
            + op
            + " ? "
        )
        params: list = [directory, start_from]
        if prefix:
            sql += "AND name GLOB ? "
            params.append(_glob_escape(prefix) + "*")
        sql += "ORDER BY name LIMIT ?"
        params.append(limit)
        with self._rlock:
            rows = self._rconn.execute(sql, params).fetchall()
        for (meta,) in rows:
            yield filer_pb2.Entry.FromString(meta)

    def count_entries(self) -> int:
        """Shard size for the fleet's per-shard accounting surface."""
        with self._rlock:
            return self._rconn.execute(
                "SELECT COUNT(*) FROM filemeta").fetchone()[0]

    def kv_get(self, key: bytes) -> bytes | None:
        with self._rlock:
            row = self._rconn.execute(
                "SELECT v FROM filer_kv WHERE k=?", (key,)
            ).fetchone()
        return row[0] if row else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if value:
                self._conn.execute(
                    "INSERT OR REPLACE INTO filer_kv (k, v) VALUES (?, ?)",
                    (key, value),
                )
            else:
                self._conn.execute("DELETE FROM filer_kv WHERE k=?", (key,))
            self._conn.commit()

    def close(self) -> None:
        if self._rconn is not self._conn:
            with self._rlock:
                self._rconn.close()
        with self._lock:
            self._conn.close()
