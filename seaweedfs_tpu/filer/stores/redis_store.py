"""Redis-backed FilerStore over the framework's own RESP client.

Reference: weed/filer/redis/universal_redis_store.go — entries live at
key = full path (serialized pb), directory membership in a set at
key = directory + "\\x00"; listing is SMEMBERS + client-side sort/page.
The go-redis dependency is replaced by util/resp.RespClient, so this
store works against any RESP2 endpoint with zero client libraries.
"""

from __future__ import annotations

from ...pb import filer_pb2
from ...util.resp import RespClient
from ..filerstore import FilerStore, register_store

DIR_LIST_MARKER = b"\x00"
KV_PREFIX = b"kv\x00"


def _entry_key(directory: str, name: str) -> bytes:
    return f"{directory.rstrip('/')}/{name}".encode()


def _dir_key(directory: str) -> bytes:
    return directory.encode() + DIR_LIST_MARKER


def _glob_escape(b: bytes) -> bytes:
    """Escape KEYS glob metacharacters so a literal path stays literal."""
    out = bytearray()
    for ch in b:
        if ch in b"*?[]\\":
            out += b"\\"
        out.append(ch)
    return bytes(out)


@register_store("redis")
class RedisStore(FilerStore):
    name = "redis"

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 db: int = 0, **_):
        # RespClient.command carries its own lock; no second layer here
        self._client = RespClient(host, port, db=db)

    def _cmd(self, *parts):
        return self._client.command(*parts)

    # -- entries -------------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._cmd(b"SET", _entry_key(directory, entry.name),
                  entry.SerializeToString())
        self._cmd(b"SADD", _dir_key(directory), entry.name.encode())

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        raw = self._cmd(b"GET", _entry_key(directory, name))
        if raw is None:
            return None
        e = filer_pb2.Entry()
        e.ParseFromString(raw)
        return e

    def delete_entry(self, directory: str, name: str) -> None:
        self._cmd(b"DEL", _entry_key(directory, name))
        self._cmd(b"SREM", _dir_key(directory), name.encode())

    def delete_folder_children(self, directory: str) -> None:
        # Primary path: targeted SMEMBERS recursion (no full-keyspace
        # scan).  A glob-ESCAPED prefix sweep then reaps keyspaces whose
        # parent entries were never created — orphans the go reference's
        # member-recursion leaves behind.
        base = directory.rstrip("/")
        for name_b in self._cmd(b"SMEMBERS", _dir_key(directory)) or []:
            name = bytes(name_b).decode()
            e = self.find_entry(directory, name)
            if e is not None and e.is_directory:
                self.delete_folder_children(f"{base}/{name}")
            self._cmd(b"DEL", _entry_key(directory, name))
        keys = self._cmd(
            b"KEYS", _glob_escape(base.encode() + b"/") + b"*") or []
        for i in range(0, len(keys), 512):  # variadic DEL batches
            self._cmd(b"DEL", *[bytes(k) for k in keys[i : i + 512]])
        self._cmd(b"DEL", _dir_key(directory))

    def list_entries(self, directory: str, start_from: str = "",
                     inclusive: bool = False, prefix: str = "",
                     limit: int = 1024):
        names = sorted(
            n.decode() for n in
            (self._cmd(b"SMEMBERS", _dir_key(directory)) or []))
        out = 0
        for name in names:
            if prefix and not name.startswith(prefix):
                continue
            if start_from:
                if name < start_from or \
                        (name == start_from and not inclusive):
                    continue
            e = self.find_entry(directory, name)
            if e is None:
                continue  # membership raced a delete
            yield e
            out += 1
            if out >= limit:
                return

    # -- KV ------------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        v = self._cmd(b"GET", KV_PREFIX + key)
        return v if v else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        if value:
            self._cmd(b"SET", KV_PREFIX + key, value)
        else:
            self._cmd(b"DEL", KV_PREFIX + key)

    def close(self) -> None:
        self._client.close()
