"""cassandra-class FilerStore over the framework-native CQL v4 client.

Reference: weed/filer/cassandra/cassandra_store.go:23-180 — a
``filemeta (directory, name, meta)`` table with PRIMARY KEY
(directory, name): the directory is the partition key, names cluster
sorted within it.  Statements mirror the reference's:
INSERT/SELECT/DELETE by (directory, name), listings by
``directory = ? AND name > ?``.  KV pairs live under a reserved NUL
directory (the reference keeps a second table; one partition is
equivalent under this store's model).

DeleteFolderChildren must remove the WHOLE subtree (the Filer calls it
once per delete, after its chunk-collection walk): descendant
partitions are discovered with ``SELECT DISTINCT directory`` — a
token-range partition-key scan on a real cluster, arriving in bounded
frames via result paging.  That scan is the cost of subtree deletes on
a partition-per-directory schema without a secondary index; the
reference's cassandra store simply leaves descendants orphaned
(cassandra_store.go DeleteFolderChildren deletes one partition), which
this framework's store contract does not allow.
"""

from __future__ import annotations

from typing import Iterator

from ...pb import filer_pb2
from ...util.cql import CqlClient
from ..filerstore import FilerStore, register_store

_KV_DIR = b"\x00kv"


@register_store("cassandra")
class CassandraStore(FilerStore):
    name = "cassandra"

    def __init__(self, host: str = "127.0.0.1", port: int = 9042,
                 keyspace: str = "seaweedfs", **_):
        self.keyspace = keyspace  # schema setup is an operator concern
        self._client = CqlClient(host, port)

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._client.query(
            "INSERT INTO filemeta (directory, name, meta) "
            "VALUES (?, ?, ?)",
            [directory.encode(), entry.name.encode(),
             entry.SerializeToString()])

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        rows = self._client.query(
            "SELECT meta FROM filemeta WHERE directory = ? AND name = ?",
            [directory.encode(), name.encode()])
        if not rows or rows[0][0] is None:
            return None
        return filer_pb2.Entry.FromString(rows[0][0])

    def delete_entry(self, directory: str, name: str) -> None:
        self._client.query(
            "DELETE FROM filemeta WHERE directory = ? AND name = ?",
            [directory.encode(), name.encode()])

    def delete_folder_children(self, directory: str) -> None:
        # one partition per directory; the subtree contract = dropping
        # every descendant partition.  SELECT DISTINCT over partition
        # keys is valid CQL (a token-range scan on a real cluster), so
        # descendants are discoverable even when intermediate directory
        # ENTRIES don't exist.
        rows = self._client.query(
            "SELECT DISTINCT directory FROM filemeta")
        want = directory.encode()
        prefix = (directory.rstrip("/") or "").encode() + b"/"
        for (d,) in rows:
            if d == want or (d or b"").startswith(prefix):
                self._client.query(
                    "DELETE FROM filemeta WHERE directory = ?", [d])

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        # page-bounded unless a prefix filter may drop rows client-side
        max_rows = None if prefix else limit
        if start_from:
            op = ">=" if inclusive else ">"
            rows = self._client.query(
                "SELECT name, meta FROM filemeta WHERE directory = ? "
                f"AND name {op} ?",
                [directory.encode(), start_from.encode()],
                max_rows=max_rows)
        else:
            rows = self._client.query(
                "SELECT name, meta FROM filemeta WHERE directory = ?",
                [directory.encode()], max_rows=max_rows)
        emitted = 0
        for name_b, meta in rows:
            name = (name_b or b"").decode()
            if prefix and not name.startswith(prefix):
                continue
            if emitted >= limit:
                return
            emitted += 1
            yield filer_pb2.Entry.FromString(meta or b"")

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        rows = self._client.query(
            "SELECT meta FROM filemeta WHERE directory = ? AND name = ?",
            [_KV_DIR, key])
        return rows[0][0] if rows else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        if value:
            self._client.query(
                "INSERT INTO filemeta (directory, name, meta) "
                "VALUES (?, ?, ?)", [_KV_DIR, key, value])
        else:
            self._client.query(
                "DELETE FROM filemeta WHERE directory = ? AND name = ?",
                [_KV_DIR, key])

    def close(self) -> None:
        self._client.close()
