"""In-memory FilerStore (tests, ephemeral filers)."""

from __future__ import annotations

import bisect
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store


@register_store("memory")
class MemoryStore(FilerStore):
    name = "memory"

    def __init__(self, **_):
        self._dirs: dict[str, dict[str, bytes]] = {}
        self._names: dict[str, list[str]] = {}  # sorted name lists
        self._kv: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        with self._lock:
            d = self._dirs.setdefault(directory, {})
            names = self._names.setdefault(directory, [])
            if entry.name not in d:
                bisect.insort(names, entry.name)
            d[entry.name] = entry.SerializeToString()

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        with self._lock:
            raw = self._dirs.get(directory, {}).get(name)
        if raw is None:
            return None
        return filer_pb2.Entry.FromString(raw)

    def delete_entry(self, directory: str, name: str) -> None:
        with self._lock:
            d = self._dirs.get(directory)
            if d and name in d:
                del d[name]
                names = self._names[directory]
                i = bisect.bisect_left(names, name)
                if i < len(names) and names[i] == name:
                    names.pop(i)

    def count_entries(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._dirs.values())

    def delete_folder_children(self, directory: str) -> None:
        with self._lock:
            prefix = directory.rstrip("/") + "/"
            for d in [directory] + [
                k for k in self._dirs if k.startswith(prefix)
            ]:
                self._dirs.pop(d, None)
                self._names.pop(d, None)

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        with self._lock:
            names = list(self._names.get(directory, ()))
            d = dict(self._dirs.get(directory, {}))
        i = bisect.bisect_left(names, start_from) if start_from else 0
        if start_from and not inclusive:
            while i < len(names) and names[i] == start_from:
                i += 1
        count = 0
        for name in names[i:]:
            if count >= limit:
                return
            if prefix and not name.startswith(prefix):
                continue
            yield filer_pb2.Entry.FromString(d[name])
            count += 1

    def kv_get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._kv.get(key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            if value:
                self._kv[key] = value
            else:
                self._kv.pop(key, None)
