"""Embedded persistent FilerStore — the reference's default-store slot.

Reference parity target: weed/filer/leveldb — the zero-dependency
embedded store a filer gets when nothing else is configured.  The design
here is NOT an LSM port: it is a bitcask-style log+snapshot store chosen
for Python's strengths —

  * all writes append to a WAL (`wal.log`), fsync'd in batches;
  * the in-RAM index maps (directory, name) -> (file, offset, length);
    entry VALUES stay on disk, so resident memory is bounded by key
    count, not metadata volume (the low-memory property the reference
    gets from leveldb);
  * when the WAL outgrows `compact_bytes`, live records are streamed
    into `snapshot.dat.tmp`, atomically renamed, and the WAL truncated
    (same shadow-file + rename discipline as volume vacuum).

Record framing (little-endian u32 lengths):
  [op u8][dlen u32][dir][nlen u32][name][vlen u32][value]
op: 1=put entry, 2=delete entry, 3=kv put (dir="", name=key), 4=delete
folder children (value empty).
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store

OP_PUT = 1
OP_DELETE = 2
OP_KV = 3
OP_DELETE_CHILDREN = 4

_SNAPSHOT = "snapshot.dat"
_WAL = "wal.log"


def _pack(op: int, directory: bytes, name: bytes, value: bytes) -> bytes:
    return b"".join((
        struct.pack("<BI", op, len(directory)), directory,
        struct.pack("<I", len(name)), name,
        struct.pack("<I", len(value)), value,
    ))


@register_store("leveldb")
class LevelDbStore(FilerStore):
    name = "leveldb"

    def __init__(self, path: str = "./filerldb",
                 compact_bytes: int = 64 << 20, **_):
        self.dir = path
        self.compact_bytes = compact_bytes
        os.makedirs(path, exist_ok=True)
        self._lock = threading.RLock()
        # (dir -> name -> (file_no, offset, length)) ; file 0 = snapshot,
        # 1 = wal.  offsets point at the VALUE bytes, not the record head.
        self._index: dict[str, dict[str, tuple[int, int, int]]] = {}
        self._names: dict[str, list[str]] = {}
        self._kv: dict[bytes, tuple[int, int, int]] = {}
        self._files = [None, None]  # read handles
        self._load()

    # -- loading / replay ---------------------------------------------------

    def _path(self, file_no: int) -> str:
        return os.path.join(self.dir, _SNAPSHOT if file_no == 0 else _WAL)

    def _load(self) -> None:
        for file_no in (0, 1):
            p = self._path(file_no)
            if not os.path.exists(p):
                open(p, "ab").close()
            self._replay(file_no)
            self._files[file_no] = open(p, "rb")
        self._wal = open(self._path(1), "ab")

    def _replay(self, file_no: int) -> None:
        """Replay records; a torn tail (crash mid-append) truncates the
        file at the last complete record instead of refusing to start —
        the same load-time healing discipline as volume torn-tail fix."""
        path = self._path(file_no)
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            record_start = 0
            while True:
                head = f.read(5)
                if len(head) < 5:
                    break
                op, dlen = struct.unpack("<BI", head)
                directory_b = f.read(dlen)
                nlen_b = f.read(4)
                if len(directory_b) < dlen or len(nlen_b) < 4:
                    break
                (nlen,) = struct.unpack("<I", nlen_b)
                name_b = f.read(nlen)
                vlen_b = f.read(4)
                if len(name_b) < nlen or len(vlen_b) < 4:
                    break
                (vlen,) = struct.unpack("<I", vlen_b)
                off = f.tell()
                if off + vlen > size:
                    break
                f.seek(vlen, os.SEEK_CUR)
                self._apply(op, directory_b.decode(), name_b,
                            (file_no, off, vlen))
                record_start = f.tell()
        if record_start < size:
            os.truncate(path, record_start)

    def _apply(self, op: int, directory: str, name_b: bytes, loc) -> None:
        name = name_b.decode()
        if op == OP_PUT:
            d = self._index.setdefault(directory, {})
            if name not in d:
                bisect.insort(self._names.setdefault(directory, []), name)
            d[name] = loc
        elif op == OP_DELETE:
            d = self._index.get(directory)
            if d and name in d:
                del d[name]
                names = self._names[directory]
                i = bisect.bisect_left(names, name)
                if i < len(names) and names[i] == name:
                    names.pop(i)
        elif op == OP_KV:
            if loc[2] == 0:
                self._kv.pop(name_b, None)
            else:
                self._kv[name_b] = loc
        elif op == OP_DELETE_CHILDREN:
            # the whole subtree: the directory itself plus descendants
            # (same contract as the sqlite store's prefix delete)
            child_prefix = directory.rstrip("/") + "/"
            for d in [k for k in self._index
                      if k == directory or k.startswith(child_prefix)]:
                self._index.pop(d, None)
                self._names.pop(d, None)

    # -- write path ---------------------------------------------------------

    def _append(self, op: int, directory: str, name_b: bytes,
                value: bytes) -> tuple[int, int, int]:
        rec = _pack(op, directory.encode(), name_b, value)
        off = self._wal.tell() + len(rec) - len(value)
        self._wal.write(rec)
        self._wal.flush()
        return (1, off, len(value))

    def _maybe_compact(self) -> None:
        # called AFTER the record is applied to the index: compaction
        # streams the index, so an unapplied record would be lost when
        # the WAL truncates
        if self._wal.tell() > self.compact_bytes:
            self._compact()

    def _read_value(self, loc: tuple[int, int, int]) -> bytes:
        file_no, off, length = loc
        f = self._files[file_no]
        f.seek(off)
        return f.read(length)

    def _compact(self) -> None:
        """Stream live records into a fresh snapshot; truncate the WAL."""
        tmp = self._path(0) + ".tmp"
        new_index: dict[str, dict[str, tuple[int, int, int]]] = {}
        new_kv: dict[bytes, tuple[int, int, int]] = {}
        with open(tmp, "wb") as out:
            for directory, names in self._index.items():
                nd = new_index.setdefault(directory, {})
                for name, loc in names.items():
                    value = self._read_value(loc)
                    rec = _pack(OP_PUT, directory.encode(), name.encode(),
                                value)
                    off = out.tell() + len(rec) - len(value)
                    out.write(rec)
                    nd[name] = (0, off, len(value))
            for key, loc in self._kv.items():
                value = self._read_value(loc)
                rec = _pack(OP_KV, b"", key, value)
                off = out.tell() + len(rec) - len(value)
                out.write(rec)
                new_kv[key] = (0, off, len(value))
            out.flush()
            os.fsync(out.fileno())
        for f in self._files:
            if f:
                f.close()
        self._wal.close()
        os.replace(tmp, self._path(0))
        os.truncate(self._path(1), 0)
        self._index = new_index
        self._kv = new_kv
        self._files = [open(self._path(0), "rb"), open(self._path(1), "rb")]
        self._wal = open(self._path(1), "ab")

    # -- FilerStore interface ----------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        with self._lock:
            name_b = entry.name.encode()
            loc = self._append(OP_PUT, directory, name_b,
                               entry.SerializeToString())
            self._apply(OP_PUT, directory, name_b, loc)
            self._maybe_compact()

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        with self._lock:
            loc = self._index.get(directory, {}).get(name)
            if loc is None:
                return None
            return filer_pb2.Entry.FromString(self._read_value(loc))

    def delete_entry(self, directory: str, name: str) -> None:
        with self._lock:
            name_b = name.encode()
            self._append(OP_DELETE, directory, name_b, b"")
            self._apply(OP_DELETE, directory, name_b, (1, 0, 0))
            self._maybe_compact()

    def delete_folder_children(self, directory: str) -> None:
        with self._lock:
            self._append(OP_DELETE_CHILDREN, directory, b"", b"")
            self._apply(OP_DELETE_CHILDREN, directory, b"", (1, 0, 0))
            self._maybe_compact()

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        with self._lock:
            names = self._names.get(directory, [])
            i = bisect.bisect_left(names, start_from) if start_from else 0
            if start_from and not inclusive:
                if i < len(names) and names[i] == start_from:
                    i += 1
            picked = []
            while i < len(names) and len(picked) < limit:
                n = names[i]
                if not prefix or n.startswith(prefix):
                    picked.append(self._index[directory][n])
                elif prefix and n > prefix and not n.startswith(prefix):
                    break
                i += 1
            values = [self._read_value(loc) for loc in picked]
        for raw in values:
            yield filer_pb2.Entry.FromString(raw)

    # -- KV -----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        with self._lock:
            loc = self._kv.get(key)
            if loc is None:
                return None
            return self._read_value(loc)

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            loc = self._append(OP_KV, "", key, value)
            if not value:
                self._kv.pop(key, None)
            else:
                self._kv[key] = loc
            self._maybe_compact()

    def close(self) -> None:
        with self._lock:
            self._wal.flush()
            os.fsync(self._wal.fileno())
            self._wal.close()
            for f in self._files:
                if f:
                    f.close()
