"""leveldb3-class FilerStore: adaptive per-bucket partitioning.

Reference: weed/filer/leveldb3/leveldb3_store.go:30-160 — one `_main` DB
for the general namespace plus one lazily-created DB per S3 bucket:
paths under ``/buckets/<bucket>/...`` route to the bucket's own DB and
are stored with the bucket prefix stripped (short path), so a bucket's
metadata lives in its own directory tree on disk.  Deleting the bucket's
subtree (`DeleteFolderChildren("/buckets/<bucket>")`) drops the whole DB
directory in O(1) instead of iterating entries — the property that makes
this the reference's preferred store for heavy S3 use.

Each partition is the framework's embedded bitcask-style store
(leveldb_store.py), living in ``dir/_main`` / ``dir/<bucket>`` exactly
like the reference's folder layout.  KV pairs always live in `_main`.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store
from .leveldb_store import LevelDbStore

DEFAULT = "_main"
_BUCKETS_PREFIX = "/buckets/"


@register_store("leveldb3")
class LevelDb3Store(FilerStore):
    name = "leveldb3"

    def __init__(self, path: str = "./filerldb3", **kw):
        self.dir = path
        self._kw = kw
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)
        self._dbs: dict[str, LevelDbStore] = {}
        # adopt bucket DBs left by a previous run
        for name in sorted(os.listdir(path)):
            if os.path.isdir(os.path.join(path, name)):
                self._dbs[name] = self._load(name)
        if DEFAULT not in self._dbs:
            self._dbs[DEFAULT] = self._load(DEFAULT)

    def _load(self, name: str) -> LevelDbStore:
        return LevelDbStore(path=os.path.join(self.dir, name), **self._kw)

    def _find_db(
        self, fullpath: str, for_children: bool = False
    ) -> tuple[LevelDbStore, str, str]:
        """-> (db, bucket, short_path); mirrors findDB
        (leveldb3_store.go:93-140).  Routing is by the ENTRY's full path —
        so `/buckets/b1/obj` (an object at bucket top level) lands in the
        b1 DB as `/obj` — while the bucket entry `/buckets/b1` itself
        stays in `_main` as a child of `/buckets`."""
        if not fullpath.startswith(_BUCKETS_PREFIX):
            return self._dbs[DEFAULT], DEFAULT, fullpath
        rest = fullpath[len(_BUCKETS_PREFIX):]
        t = rest.find("/")
        if t < 0 and not for_children:
            # `/buckets/<bucket>` as an ENTRY lives in its parent's
            # partition (_main); as a listing target it is the bucket root
            return self._dbs[DEFAULT], DEFAULT, fullpath
        bucket = rest if t < 0 else rest[:t]
        short = "/" if t < 0 else rest[t:]
        with self._lock:
            db = self._dbs.get(bucket)
            if db is None:
                db = self._dbs[bucket] = self._load(bucket)
        return db, bucket, short

    @staticmethod
    def _join(directory: str, name: str) -> str:
        return (directory.rstrip("/") or "") + "/" + name

    @staticmethod
    def _split(short: str) -> tuple[str, str]:
        i = short.rfind("/")
        return (short[:i] or "/", short[i + 1:])

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        db, _, short = self._find_db(self._join(directory, entry.name))
        sdir, _ = self._split(short)
        db.insert_entry(sdir, entry)

    def update_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        db, _, short = self._find_db(self._join(directory, entry.name))
        sdir, _ = self._split(short)
        db.update_entry(sdir, entry)

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        db, _, short = self._find_db(self._join(directory, name))
        sdir, sname = self._split(short)
        return db.find_entry(sdir, sname)

    def delete_entry(self, directory: str, name: str) -> None:
        db, _, short = self._find_db(self._join(directory, name))
        sdir, sname = self._split(short)
        db.delete_entry(sdir, sname)

    def delete_folder_children(self, directory: str) -> None:
        norm = directory.rstrip("/") or "/"
        if norm in ("/", "/buckets"):
            # the subtree covers EVERY bucket: drop all bucket DBs, not
            # just the _main stubs — otherwise recreating a bucket would
            # lazily re-open its old DB and resurrect deleted objects
            with self._lock:
                buckets = [b for b in self._dbs if b != DEFAULT]
                dbs = [self._dbs.pop(b) for b in buckets]
            for db in dbs:
                db.close()
            for b in buckets:
                shutil.rmtree(os.path.join(self.dir, b),
                              ignore_errors=True)
            self._dbs[DEFAULT].delete_folder_children(directory)
            return
        db, bucket, short = self._find_db(directory, for_children=True)
        if bucket != DEFAULT and short == "/":
            # whole-bucket delete: drop the DB directory in O(1)
            # (leveldb3_store.go:248-261)
            with self._lock:
                db = self._dbs.pop(bucket, None)
            if db is not None:
                db.close()
            shutil.rmtree(os.path.join(self.dir, bucket),
                          ignore_errors=True)
            return
        db.delete_folder_children(short)

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        db, _, short = self._find_db(directory, for_children=True)
        return db.list_entries(
            short, start_from=start_from, inclusive=inclusive,
            prefix=prefix, limit=limit)

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        return self._dbs[DEFAULT].kv_get(key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._dbs[DEFAULT].kv_put(key, value)

    def close(self) -> None:
        with self._lock:
            dbs, self._dbs = list(self._dbs.values()), {}
        for db in dbs:
            db.close()
