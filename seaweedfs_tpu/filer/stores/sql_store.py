"""Generic SQL FilerStore over any DB-API 2.0 driver — the abstract_sql
class, plus its mysql and postgres kinds.

Reference: weed/filer/abstract_sql/abstract_sql_store.go (one shared SQL
implementation) specialised by weed/filer/mysql/ and weed/filer/postgres/
(dialect: placeholder style + upsert clause).  The schema matches the
scaffold's `filemeta(dirhash BIGINT, name, directory, meta)` with the
md5-prefix directory hash of util.HashStringToLong (weed/util/bytes.go:73)
leading the primary key, so lookups and listings hit one (dirhash, name)
index range regardless of directory-string length.

The mysql / postgres kinds import their client library lazily and raise a
loud ConfigurationError when it is absent (this image ships neither); the
shared SQL layer itself is fully exercised in tests through the stdlib
sqlite3 driver, which is DB-API 2.0 like the others.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store


class ConfigurationError(RuntimeError):
    pass


def hash_string_to_long(directory: str) -> int:
    """First 8 md5 bytes, big-endian, as a SIGNED 64-bit int
    (util.HashStringToLong, weed/util/bytes.go:73)."""
    b = hashlib.md5(directory.encode()).digest()
    return int.from_bytes(b[:8], "big", signed=True)


def _like_escape(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("%", r"\%").replace("_", r"\_"))


class Dialect:
    """What actually differs between SQL backends."""

    paramstyle = "?"  # sqlite; mysql/postgres use %s
    upsert_suffix = ""  # appended to the INSERT for insert-or-replace
    insert_verb = "INSERT OR REPLACE"
    blob_type = "BLOB"
    like_escape_clause = " ESCAPE '\\'"

    def placeholders(self, n: int) -> list[str]:
        return [self.paramstyle] * n


class SqliteDialect(Dialect):
    pass


class MysqlDialect(Dialect):
    paramstyle = "%s"
    insert_verb = "INSERT"
    upsert_suffix = " ON DUPLICATE KEY UPDATE meta=VALUES(meta)"
    blob_type = "LONGBLOB"
    like_escape_clause = ""  # backslash is mysql's default escape


class PostgresDialect(Dialect):
    paramstyle = "%s"
    insert_verb = "INSERT"
    upsert_suffix = (
        " ON CONFLICT (dirhash, name) DO UPDATE SET meta=EXCLUDED.meta"
    )
    blob_type = "BYTEA"


class AbstractSqlStore(FilerStore):
    """The shared SQL implementation; a kind supplies (connection, dialect)."""

    name = "sql"

    def __init__(self, conn, dialect: Dialect):
        self._conn = conn
        self._d = dialect
        self._lock = threading.RLock()
        self._in_tx = False
        p = dialect.paramstyle
        # plain INSERT + directory-scoped UPDATE fallback, NOT an upsert:
        # the PK is (dirhash, name), so a blind upsert would let a 64-bit
        # dirhash collision between two directories silently replace the
        # other directory's row; the reference instead updates WHERE
        # dirhash AND name AND directory and errors when that matches
        # nothing (abstract_sql_store.go InsertEntry fallback)
        self._sql_insert = (
            "INSERT INTO filemeta "
            f"(dirhash, name, directory, meta) VALUES ({p}, {p}, {p}, {p})"
        )
        self._sql_update = (
            f"UPDATE filemeta SET meta={p} WHERE dirhash={p} AND name={p}"
            f" AND directory={p}"
        )
        self._sql_find_dir = (
            f"SELECT directory FROM filemeta WHERE dirhash={p} AND name={p}"
        )
        # dirhash is a 64-bit hash — always scope by the directory column
        # too, so a hash collision between two directories cannot return or
        # delete another directory's entry (the reference's SQL gens do the
        # same, mysql_sql_gen.go:33)
        self._sql_find = (
            f"SELECT meta FROM filemeta WHERE dirhash={p} AND name={p}"
            f" AND directory={p}"
        )
        self._sql_delete = (
            f"DELETE FROM filemeta WHERE dirhash={p} AND name={p}"
            f" AND directory={p}"
        )
        self._sql_delete_tree = (
            f"DELETE FROM filemeta WHERE directory={p} OR directory LIKE {p}"
            f"{dialect.like_escape_clause}"
        )
        self._sql_kv_get = f"SELECT v FROM filer_kv WHERE k={p}"
        self._sql_kv_del = f"DELETE FROM filer_kv WHERE k={p}"
        self._sql_kv_put = (
            f"{dialect.insert_verb} INTO filer_kv (k, v) VALUES ({p}, {p})"
            + (dialect.upsert_suffix
               .replace("(dirhash, name)", "(k)")
               .replace("meta", "v"))
        )
        self._ensure_schema()

    def _ensure_schema(self) -> None:
        blob = self._d.blob_type
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(
                "CREATE TABLE IF NOT EXISTS filemeta ("
                " dirhash BIGINT NOT NULL,"
                " name VARCHAR(766) NOT NULL,"
                " directory TEXT NOT NULL,"
                f" meta {blob} NOT NULL,"
                " PRIMARY KEY (dirhash, name))"
            )
            cur.execute(
                "CREATE TABLE IF NOT EXISTS filer_kv ("
                f" k VARCHAR(766) NOT NULL PRIMARY KEY, v {blob} NOT NULL)"
            )
            self._conn.commit()

    def _commit(self) -> None:
        if not self._in_tx:
            self._conn.commit()

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        dirhash = hash_string_to_long(directory)
        meta = entry.SerializeToString()
        with self._lock:
            # check-then-act, retried once: the existence check (not
            # insert-then-catch) distinguishes a legitimate rewrite from
            # a cross-directory dirhash collision without relying on
            # driver-specific duplicate-key errors; the retry absorbs a
            # concurrent writer from ANOTHER process (two filers on one
            # DB) whose insert lands between our check and insert
            for attempt in range(2):
                cur = self._conn.cursor()
                cur.execute(self._sql_find_dir, (dirhash, entry.name))
                row = cur.fetchone()
                if row is None:
                    try:
                        cur.execute(self._sql_insert,
                                    (dirhash, entry.name, directory, meta))
                    except Exception:
                        # likely a cross-process duplicate-key race:
                        # clear any poisoned implicit transaction and
                        # re-run the check, which now sees the row
                        if not self._in_tx:
                            try:
                                self._conn.rollback()
                            except Exception:
                                pass
                        if attempt == 0:
                            continue
                        raise
                elif str(row[0]) == directory:
                    cur.execute(self._sql_update,
                                (meta, dirhash, entry.name, directory))
                else:
                    raise ValueError(
                        f"dirhash collision: ({directory!r}, "
                        f"{entry.name!r}) conflicts with {str(row[0])!r}")
                break
            self._commit()

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(self._sql_find,
                        (hash_string_to_long(directory), name, directory))
            row = cur.fetchone()
        if row is None:
            return None
        return filer_pb2.Entry.FromString(bytes(row[0]))

    def delete_entry(self, directory: str, name: str) -> None:
        with self._lock:
            self._conn.cursor().execute(
                self._sql_delete,
                (hash_string_to_long(directory), name, directory))
            self._commit()

    def delete_folder_children(self, directory: str) -> None:
        prefix = directory.rstrip("/") + "/"
        with self._lock:
            self._conn.cursor().execute(
                self._sql_delete_tree,
                (directory, _like_escape(prefix) + "%"))
            self._commit()

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        p = self._d.paramstyle
        op = ">=" if inclusive else ">"
        sql = (f"SELECT meta FROM filemeta WHERE dirhash={p} "
               f"AND directory={p} AND name {op} {p} ")
        params: list = [hash_string_to_long(directory), directory, start_from]
        if prefix:
            sql += f"AND name LIKE {p}{self._d.like_escape_clause} "
            params.append(_like_escape(prefix) + "%")
        sql += f"ORDER BY name LIMIT {p}"
        params.append(limit)
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(sql, params)
            rows = cur.fetchall()
        for (meta,) in rows:
            yield filer_pb2.Entry.FromString(bytes(meta))

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        with self._lock:
            cur = self._conn.cursor()
            cur.execute(self._sql_kv_get, (key.decode("latin-1"),))
            row = cur.fetchone()
        return bytes(row[0]) if row else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        with self._lock:
            cur = self._conn.cursor()
            if value:
                cur.execute(self._sql_kv_put,
                            (key.decode("latin-1"), value))
            else:
                cur.execute(self._sql_kv_del, (key.decode("latin-1"),))
            self._commit()

    # -- transactions -------------------------------------------------------

    def begin(self) -> None:
        self._in_tx = True

    def commit(self) -> None:
        with self._lock:
            self._conn.commit()
        self._in_tx = False

    def rollback(self) -> None:
        with self._lock:
            self._conn.rollback()
        self._in_tx = False

    def close(self) -> None:
        with self._lock:
            self._conn.close()


@register_store("mysql")
class MysqlStore(AbstractSqlStore):
    """filer store over a MySQL server (weed/filer/mysql/)."""

    name = "mysql"

    def __init__(self, hostname: str = "localhost", port: int = 3306,
                 username: str = "root", password: str = "",
                 database: str = "seaweedfs", **_):
        try:
            import pymysql  # type: ignore[import-not-found]
        except ImportError:
            try:
                import MySQLdb as pymysql  # type: ignore[import-not-found]
            except ImportError:
                raise ConfigurationError(
                    "filer store 'mysql' needs the pymysql or mysqlclient "
                    "package, which this image does not ship; the SQL "
                    "layer itself is the tested abstract_sql class"
                ) from None
        conn = pymysql.connect(host=hostname, port=port, user=username,
                               password=password, database=database)
        super().__init__(conn, MysqlDialect())


@register_store("postgres")
class PostgresStore(AbstractSqlStore):
    """filer store over a PostgreSQL server (weed/filer/postgres/)."""

    name = "postgres"

    def __init__(self, hostname: str = "localhost", port: int = 5432,
                 username: str = "postgres", password: str = "",
                 database: str = "seaweedfs", **_):
        try:
            import psycopg2  # type: ignore[import-not-found]
        except ImportError:
            raise ConfigurationError(
                "filer store 'postgres' needs the psycopg2 package, which "
                "this image does not ship; the SQL layer itself is the "
                "tested abstract_sql class"
            ) from None
        conn = psycopg2.connect(host=hostname, port=port, user=username,
                                password=password, dbname=database)
        super().__init__(conn, PostgresDialect())
