"""etcd-backed FilerStore over the framework-native etcd v3 client.

Reference: weed/filer/etcd/etcd_store.go:23-207 — entries live at
``<directory>\\x00<name>`` keys holding pb-encoded Entry bytes; listing
and subtree deletion are prefix range ops.  KV pairs get their own
``kv\\x00`` namespace (the reference store puts them beside entries;
a disjoint prefix keeps a kv key from ever shadowing an entry).

Works against a stock etcd cluster (the client speaks real
etcdserverpb.KV) or the in-process FakeEtcdServer in tests.
"""

from __future__ import annotations

from typing import Iterator

from ...pb import filer_pb2
from ...util.etcd import EtcdClient
from ..filerstore import FilerStore, register_store

SEP = b"\x00"  # DIR_FILE_SEPARATOR (etcd_store.go:190)
_KV_PREFIX = b"kv" + SEP


def _key(directory: str, name: str) -> bytes:
    return directory.encode() + SEP + name.encode()


def _dir_prefix(directory: str, start: str = "") -> bytes:
    return directory.encode() + SEP + start.encode()


@register_store("etcd")
class EtcdStore(FilerStore):
    name = "etcd"

    def __init__(self, servers: str = "127.0.0.1:2379",
                 timeout: float = 10.0, **_):
        self._client = EtcdClient(servers.split(",")[0], timeout=timeout)

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._client.put(_key(directory, entry.name),
                         entry.SerializeToString())

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        blob = self._client.get(_key(directory, name))
        if blob is None:
            return None
        return filer_pb2.Entry.FromString(blob)

    def delete_entry(self, directory: str, name: str) -> None:
        self._client.delete(_key(directory, name))

    def delete_folder_children(self, directory: str) -> None:
        # children of the directory itself...
        self._client.delete_prefix(_dir_prefix(directory))
        # ...and every descendant directory's children (their keys start
        # with "<directory>/"): one ranged delete covers the subtree
        self._client.delete_prefix(
            (directory.rstrip("/") + "/").encode())

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        prefix_key = _dir_prefix(directory, prefix)
        start = _dir_prefix(directory, start_from) if start_from else b""
        # clamp: a marker sorting BEFORE the prefix must not let
        # pre-prefix keys consume the server-side limit (S3 listings
        # pass marker+prefix combinations shaped exactly like this)
        start = max(start, prefix_key)
        fetched = self._client.range_prefix(
            prefix_key, start=start,
            limit=limit + 1 if start_from else limit)
        count = 0
        for k, v in fetched:
            name = k.split(SEP, 1)[1].decode()
            if start_from:
                if name < start_from or (name == start_from
                                         and not inclusive):
                    continue
            if prefix and not name.startswith(prefix):
                continue
            if count >= limit:
                return
            count += 1
            yield filer_pb2.Entry.FromString(v)

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        return self._client.get(_KV_PREFIX + key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        if value:
            self._client.put(_KV_PREFIX + key, value)
        else:
            self._client.delete(_KV_PREFIX + key)
