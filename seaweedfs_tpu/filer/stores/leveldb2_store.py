"""leveldb2-class FilerStore: the embedded store, hash-partitioned 8 ways.

Reference: weed/filer/leveldb2/leveldb2_store.go — same metadata model as
leveldb but the keyspace is split across 8 independent DB instances, with
the LAST md5 byte of the directory choosing the partition
(leveldb2_store.go hashToBytes), so compactions and locks shard with
directory locality and the write path scales across instances.

Here each partition is one of the framework's bitcask-style embedded
stores (leveldb_store.py) living in a numbered subdirectory, exactly the
reference's `dir/00 .. dir/07` layout.  KV pairs route by the same hash of
the key's text form.
"""

from __future__ import annotations

import hashlib
import os
from typing import Iterator

from ...pb import filer_pb2
from ..filerstore import FilerStore, register_store
from .leveldb_store import LevelDbStore


@register_store("leveldb2")
class LevelDb2Store(FilerStore):
    name = "leveldb2"

    def __init__(self, path: str = "./filerldb2", db_count: int = 8, **kw):
        self.dir = path
        self.db_count = db_count
        self._dbs = [
            LevelDbStore(path=os.path.join(path, f"{i:02d}"), **kw)
            for i in range(db_count)
        ]

    def _db(self, directory: str) -> LevelDbStore:
        # last md5 byte picks the partition (leveldb2_store.go hashToBytes)
        x = hashlib.md5(directory.encode()).digest()[-1]
        return self._dbs[x % self.db_count]

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._db(directory).insert_entry(directory, entry)

    def update_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._db(directory).update_entry(directory, entry)

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        return self._db(directory).find_entry(directory, name)

    def delete_entry(self, directory: str, name: str) -> None:
        self._db(directory).delete_entry(directory, name)

    def delete_folder_children(self, directory: str) -> None:
        # children of one directory share a partition, but DESCENDANT
        # directories hash elsewhere — the subtree delete must visit all
        for db in self._dbs:
            db.delete_folder_children(directory)

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        return self._db(directory).list_entries(
            directory, start_from=start_from, inclusive=inclusive,
            prefix=prefix, limit=limit)

    # -- kv ----------------------------------------------------------------

    def _kv_db(self, key: bytes) -> LevelDbStore:
        x = hashlib.md5(key).digest()[-1]
        return self._dbs[x % self.db_count]

    def kv_get(self, key: bytes) -> bytes | None:
        return self._kv_db(key).kv_get(key)

    def kv_put(self, key: bytes, value: bytes) -> None:
        self._kv_db(key).kv_put(key, value)

    def close(self) -> None:
        for db in self._dbs:
            db.close()
