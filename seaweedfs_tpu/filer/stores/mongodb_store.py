"""mongodb-class FilerStore over the framework-native OP_MSG client.

Reference: weed/filer/mongodb/mongodb_store.go:29-200 — documents
``{directory, name, meta}`` in the ``filemeta`` collection with a unique
(directory, name) index; find/upsert/delete by exact (directory, name),
listings by ``{directory, name: {$gt: start}}`` sorted on name.  KV
pairs reuse the same collection under a reserved directory (the
reference stores them as ``{directory: "", name: hex(key)}``-shaped
rows via the same model).

The reference's DeleteFolderChildren removes only DIRECT children; this
framework's Filer contract expects the whole subtree, so the store adds
a ranged ``$or`` over the descendant prefix — same observable behavior
as the other nine backends.
"""

from __future__ import annotations

from typing import Iterator

from ...pb import filer_pb2
from ...util.mongo import MongoClient
from ..filerstore import FilerStore, register_store

COLLECTION = "filemeta"
_KV_DIR = "\x00kv"  # reserved namespace: no real path starts with NUL


def _subtree_filter(directory: str) -> dict:
    prefix = directory.rstrip("/") + "/"
    end = prefix[:-1] + chr(ord(prefix[-1]) + 1)
    return {"$or": [
        {"directory": directory},
        {"directory": {"$gte": prefix, "$lt": end}},
    ]}


@register_store("mongodb")
class MongodbStore(FilerStore):
    name = "mongodb"

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "seaweedfs", **_):
        self._client = MongoClient(host, port, database=database)

    # -- entries -----------------------------------------------------------

    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self._client.upsert(
            COLLECTION,
            {"directory": directory, "name": entry.name},
            {"meta": entry.SerializeToString()},
        )

    update_entry = insert_entry

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        rows = self._client.find(
            COLLECTION, {"directory": directory, "name": name}, limit=1)
        if not rows:
            return None
        return filer_pb2.Entry.FromString(rows[0]["meta"])

    def delete_entry(self, directory: str, name: str) -> None:
        self._client.delete(
            COLLECTION, {"directory": directory, "name": name})

    def delete_folder_children(self, directory: str) -> None:
        self._client.delete(COLLECTION, _subtree_filter(directory),
                            many=True)

    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]:
        # push BOTH bounds to the server: the name conditions combine the
        # start cursor with a [prefix, prefix-end) range, and the limit
        # rides the find command — no whole-directory transfers
        conds: dict = {}
        if prefix:
            conds["$gte"] = prefix
            try:
                end = prefix[:-1] + chr(ord(prefix[-1]) + 1)
                end.encode()  # reject lone surrogates before BSON does
                conds["$lt"] = end
            except (ValueError, UnicodeEncodeError):
                pass  # boundary codepoint: $gte + startswith belt suffice
        if start_from:
            if inclusive:
                conds["$gte"] = max(conds.get("$gte", ""), start_from)
            else:
                conds["$gt"] = start_from
        flt: dict = {"directory": directory}
        if conds:
            flt["name"] = conds
        emitted = 0
        rows = self._client.find(COLLECTION, flt, sort={"name": 1},
                                 limit=limit)
        for row in rows:
            if prefix and not row["name"].startswith(prefix):
                continue  # belt: e.g. multi-byte prefix-end edge
            if emitted >= limit:
                return
            emitted += 1
            yield filer_pb2.Entry.FromString(row["meta"])

    # -- kv ----------------------------------------------------------------

    def kv_get(self, key: bytes) -> bytes | None:
        rows = self._client.find(
            COLLECTION,
            {"directory": _KV_DIR, "name": key.hex()}, limit=1)
        return bytes(rows[0]["meta"]) if rows else None

    def kv_put(self, key: bytes, value: bytes) -> None:
        if value:
            self._client.upsert(
                COLLECTION, {"directory": _KV_DIR, "name": key.hex()},
                {"meta": value})
        else:
            self._client.delete(
                COLLECTION, {"directory": _KV_DIR, "name": key.hex()})

    def close(self) -> None:
        self._client.close()
