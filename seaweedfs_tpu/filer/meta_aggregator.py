"""Multi-filer metadata federation.

Reference: weed/filer/meta_aggregator.go — every filer follows each peer's
SubscribeLocalMetadata stream (self included).  Events land in an
aggregate log that backs the public SubscribeMetadata rpc, and — when the
peer runs its OWN store (different store signature) — are replayed
directly into the local store so the namespaces converge.  Replays write
to the store, not through the Filer mutation path, so they emit no local
events: that is the loop prevention.  Per-peer resume offsets persist in
the store's KV under b"Meta" + the peer's 4-byte signature.
"""

from __future__ import annotations

import struct
import threading
import time

import grpc

from ..pb import filer_pb2
from ..pb import rpc as rpclib
from ..util import glog
from .meta_log import MetaLogBuffer

META_OFFSET_PREFIX = b"Meta"
RETRY_SECONDS = 1.4


def _offset_key(peer_signature: int) -> bytes:
    return META_OFFSET_PREFIX + struct.pack(">i", peer_signature)


def _move_subtree(store, old_path: str, new_path: str) -> None:
    """Re-root every child of old_path under new_path (replica side of a
    directory rename, which emits ONE event for the directory itself)."""
    stack = [(old_path, new_path)]
    while stack:
        src, dst = stack.pop()
        start = ""
        while True:
            batch = list(store.list_entries(src, start_from=start,
                                            limit=1024))
            if not batch:
                break
            for e in batch:
                store.insert_entry(dst, e)
                if e.is_directory:
                    stack.append((f"{src}/{e.name}", f"{dst}/{e.name}"))
            start = batch[-1].name
    store.delete_folder_children(old_path)


def replay_event(store, resp: filer_pb2.SubscribeMetadataResponse) -> None:
    """Apply one remote mutation directly to the local store
    (filer.Replay analogue): delete the old entry, insert the new one at
    its (possibly moved) parent.  Directory events stand for their whole
    subtree — the originating filer emits a single event for a recursive
    delete or rename (filer.py delete_entry/rename_entry), so the replica
    must mirror the subtree operation here."""
    n = resp.event_notification
    directory = resp.directory
    old_name = n.old_entry.name
    new_name = n.new_entry.name
    moved = bool(old_name and new_name and (
        n.new_parent_path not in ("", directory) or old_name != new_name))
    if old_name and (not new_name or moved):
        old_path = f"{directory.rstrip('/')}/{old_name}"
        if n.old_entry.is_directory:
            if moved:
                target_dir = (n.new_parent_path or directory).rstrip("/")
                _move_subtree(store, old_path, f"{target_dir}/{new_name}")
            else:
                store.delete_folder_children(old_path)
        store.delete_entry(directory, old_name)
    if new_name:
        target_dir = n.new_parent_path or directory
        store.insert_entry(target_dir, n.new_entry)


class MetaAggregator:
    def __init__(self, store, signature: int, self_grpc_address: str,
                 peer_grpc_addresses: list[str]):
        self.store = store
        self.signature = signature
        self.self_address = self_grpc_address
        # self is always followed too: the aggregate log then carries the
        # full merged stream and SubscribeMetadata reads only from it
        self.peers = list(dict.fromkeys(
            [self_grpc_address, *peer_grpc_addresses]))
        self.log = MetaLogBuffer()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        for peer in self.peers:
            t = threading.Thread(
                target=self._follow, args=(peer,),
                name=f"meta-aggregate-{peer}", daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    # -- one peer ------------------------------------------------------------

    def _peer_signature(self, peer: str) -> int | None:
        try:
            resp = rpclib.filer_stub(peer, timeout=10).GetFilerConfiguration(
                filer_pb2.GetFilerConfigurationRequest())
            return resp.signature
        except grpc.RpcError:
            return None

    def _read_offset(self, peer_signature: int) -> int:
        raw = self.store.kv_get(_offset_key(peer_signature))
        if raw and len(raw) == 8:
            return struct.unpack(">q", raw)[0]
        return 0

    def _write_offset(self, peer_signature: int, ts_ns: int) -> None:
        self.store.kv_put(_offset_key(peer_signature),
                                struct.pack(">q", ts_ns))

    def _follow(self, peer: str) -> None:
        # resolve the peer's store signature first (retry until up)
        sig = self._peer_signature(peer)
        while sig is None and not self._stop.wait(RETRY_SECONDS):
            sig = self._peer_signature(peer)
        if sig is None:
            return
        replicate = sig != self.signature
        # self-follow starts from 0 so the aggregate log carries the full
        # local backlog (SubscribeMetadata must not lose pre-start events)
        last_ts = self._read_offset(sig) if replicate else 0
        if replicate:
            glog.info("filer follows peer %s sig=%d since=%d",
                      peer, sig, last_ts)
        fail_ts, fail_count = 0, 0
        ingest_ts = 0
        persisted_ts = last_ts
        pending = 0
        last_persist = time.monotonic()

        def persist(ts: int, force: bool = False) -> None:
            # offset writes are throttled (replay is idempotent over the
            # re-delivery window) — per-event kv_puts would double the
            # store write load during bulk replication
            nonlocal persisted_ts, pending, last_persist
            pending += 1
            if force or pending >= 100 or \
                    time.monotonic() - last_persist > 2.0:
                if ts > persisted_ts:
                    self._write_offset(sig, ts)
                    persisted_ts = ts
                pending = 0
                last_persist = time.monotonic()

        while not self._stop.is_set():
            try:
                stream = rpclib.filer_stub(peer).SubscribeLocalMetadata(
                    filer_pb2.SubscribeMetadataRequest(
                        client_name=f"filer:{self.self_address}",
                        path_prefix="/",
                        since_ns=last_ts,
                    )
                )
                for resp in stream:
                    if self._stop.is_set():
                        return
                    # a replay-retry reconnect re-delivers events already
                    # ingested; only new timestamps enter the aggregate
                    if resp.ts_ns > ingest_ts:
                        self.log.ingest(resp)
                        ingest_ts = resp.ts_ns
                    if replicate:
                        try:
                            replay_event(self.store, resp)
                        except Exception as e:  # noqa: BLE001
                            # do NOT advance the offset past a failed
                            # replay — reconnect and retry it, giving up
                            # only on a poison event (3 strikes)
                            if resp.ts_ns == fail_ts:
                                fail_count += 1
                            else:
                                fail_ts, fail_count = resp.ts_ns, 1
                            if fail_count < 3:
                                glog.warning(
                                    "replay from %s failed (try %d): %s",
                                    peer, fail_count, e)
                                break
                            glog.error(
                                "replay from %s failed 3x, skipping "
                                "event ts=%d: %s", peer, resp.ts_ns, e)
                        persist(resp.ts_ns)
                    last_ts = resp.ts_ns
            except grpc.RpcError:
                pass
            if replicate:
                persist(last_ts, force=True)
            if self._stop.wait(RETRY_SECONDS):
                return
