"""FilerStore: the pluggable metadata backend interface.

Reference: weed/filer/filerstore.go:18-41 — InsertEntry/UpdateEntry/
FindEntry/DeleteEntry/DeleteFolderChildren/ListDirectoryEntries + KV +
transactions.  Stores persist pb-serialized Entry bytes keyed by
(directory, name); backends register by name like the reference's
blank-import init() plugin pattern (weed/server/filer_server.go:23-36).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Callable, Iterator

from ..pb import filer_pb2

_REGISTRY: dict[str, Callable[..., "FilerStore"]] = {}


def register_store(name: str):
    def deco(cls):
        _REGISTRY[name] = cls
        return cls

    return deco


def make_store(name: str, **kwargs) -> "FilerStore":
    # import for registration side effects
    from . import stores  # noqa: F401

    try:
        return _REGISTRY[name](**kwargs)
    except KeyError:
        raise ValueError(
            f"unknown filer store {name!r}; have {sorted(_REGISTRY)}"
        ) from None


class FilerStore(ABC):
    name = "abstract"

    @abstractmethod
    def insert_entry(self, directory: str, entry: filer_pb2.Entry) -> None: ...

    @abstractmethod
    def update_entry(self, directory: str, entry: filer_pb2.Entry) -> None: ...

    @abstractmethod
    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None: ...

    @abstractmethod
    def delete_entry(self, directory: str, name: str) -> None: ...

    @abstractmethod
    def delete_folder_children(self, directory: str) -> None: ...

    @abstractmethod
    def list_entries(
        self,
        directory: str,
        start_from: str = "",
        inclusive: bool = False,
        prefix: str = "",
        limit: int = 1024,
    ) -> Iterator[filer_pb2.Entry]: ...

    def count_entries(self) -> int | None:
        """Total entries in this store, or None when the backend cannot
        answer cheaply (fleet shard-size accounting is best-effort)."""
        return None

    # -- KV ----------------------------------------------------------------

    @abstractmethod
    def kv_get(self, key: bytes) -> bytes | None: ...

    @abstractmethod
    def kv_put(self, key: bytes, value: bytes) -> None: ...

    def kv_delete(self, key: bytes) -> None:
        self.kv_put(key, b"")

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        pass

    # transactions are no-ops for embedded stores
    def begin(self) -> None:
        pass

    def commit(self) -> None:
        pass

    def rollback(self) -> None:
        pass
