"""Manifest chunks: indirection blobs that keep huge chunk lists out of
the metadata store.

Reference: weed/filer/filechunk_manifest.go — when an entry accumulates
more than `manifest_batch` chunks, batches of them are serialized into a
FileChunkManifest blob, uploaded like any other chunk, and replaced by a
single FileChunk with is_chunk_manifest=true spanning the batch's byte
range.  Readers resolve manifests (recursively — a manifest of manifests
is legal) back into the real chunk list before interval resolution.
"""

from __future__ import annotations

import gzip

from ..pb import filer_pb2
from . import filechunks

MANIFEST_BATCH = 1000  # filechunk_manifest.go ManifestBatch


def has_chunk_manifest(chunks) -> bool:
    return any(c.is_chunk_manifest for c in chunks)


def separate_manifest_chunks(chunks) -> tuple[list, list]:
    """-> (manifest_chunks, non_manifest_chunks)."""
    manifests, plain = [], []
    for c in chunks:
        (manifests if c.is_chunk_manifest else plain).append(c)
    return manifests, plain


def resolve_chunk_manifest(fetch_fn, chunks, recursion: int = 0) -> list:
    """Expand manifest chunks into their real chunk lists.

    ``fetch_fn(file_id) -> bytes`` fetches a whole blob (usually through
    the chunk cache).  Depth-limited: legitimate data never nests deeper
    than a few levels; a cycle in corrupted metadata must not hang.
    """
    if recursion > 10:
        raise IOError("chunk manifest nesting too deep (corrupt metadata?)")
    out = []
    for c in chunks:
        if not c.is_chunk_manifest:
            out.append(c)
            continue
        m = filer_pb2.FileChunkManifest()
        m.ParseFromString(gzip.decompress(fetch_fn(c.file_id)))
        resolved = resolve_chunk_manifest(fetch_fn, list(m.chunks),
                                          recursion + 1)
        out.extend(resolved)
    return out


def maybe_manifestize(save_fn, chunks,
                      manifest_batch: int = MANIFEST_BATCH) -> list:
    """Batch plain chunks into manifest chunks when the list is long.

    ``save_fn(data: bytes) -> filer_pb2.FileChunk`` uploads a blob and
    returns its chunk record (offset/size are overwritten here).  Already-
    manifest chunks pass through untouched; only full batches are folded,
    so a file growing by appends re-manifestizes amortized-once.
    """
    manifests, plain = separate_manifest_chunks(chunks)
    if len(plain) <= manifest_batch:
        return list(chunks)
    plain.sort(key=lambda c: c.offset)
    out = list(manifests)
    pos = 0
    while len(plain) - pos > manifest_batch:
        batch = plain[pos : pos + manifest_batch]
        out.append(_manifestize_batch(save_fn, batch))
        pos += manifest_batch
    out.extend(plain[pos:])
    return out


def _manifestize_batch(save_fn, batch) -> filer_pb2.FileChunk:
    m = filer_pb2.FileChunkManifest()
    m.chunks.extend(batch)
    blob = gzip.compress(m.SerializeToString(), compresslevel=3)
    chunk = save_fn(blob)
    chunk.is_chunk_manifest = True
    chunk.offset = min(c.offset for c in batch)
    chunk.size = filechunks.total_size(batch) - chunk.offset
    chunk.mtime = max(c.mtime for c in batch)
    return chunk
