"""Filer metadata event log: durable sequenced segments + live tailing.

Reference: weed/filer/filer_notify.go + weed/util/log_buffer — every
mutation appends an EventNotification with a monotonic ts_ns; subscribers
replay events since a timestamp, then tail live.

This implementation (ISSUE 12) adds a DURABLE layer under the in-memory
ring: when constructed with ``dir=``, every appended/ingested event is
framed (crc32 + length + sequence + ts) and written to fsynced segment
files, so

* sequence numbers are monotonic, persisted, and GAP-DETECTABLE — a
  consumer resuming from a checkpoint either gets a contiguous stream or
  a loud ``MetaLogGap`` (never a silent hole);
* history survives restarts and ring eviction: ``subscribe``/``tail``
  serve old events from disk, then hand off to the live ring;
* retention is bounded (``SEAWEEDFS_TPU_META_LOG_RETAIN_MB``): whole
  oldest segments are dropped, advancing ``first_retained_seq``.

The ts_ns stamp doubles as the HYBRID LOGICAL CLOCK the geo plane's
last-writer-wins resolution compares: ``append`` stamps
``max(wall_clock, last+1)`` and ``observe`` advances the clock past any
remote timestamp applied locally, so causality between clusters is never
inverted by wall-clock skew (replication/geo.py).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from collections import deque

from ..pb import filer_pb2

from ..util import glog

# record framing on disk: crc32(payload) | payload_len | seq | ts_ns
_REC_HEADER = struct.Struct(">IIQq")

_SEG_PREFIX = "seg-"
_SEG_SUFFIX = ".log"

SEGMENT_BYTES = int(os.environ.get(
    "SEAWEEDFS_TPU_META_LOG_SEGMENT_MB", "4")) << 20
RETAIN_BYTES = int(os.environ.get(
    "SEAWEEDFS_TPU_META_LOG_RETAIN_MB", "64")) << 20
# fsync per append keeps the durability claim honest against HOST power
# loss (page-cache writes already survive process SIGKILL); the filer
# server pays it only when geo replication is on — =0/=1 here overrides
# that default either way
FSYNC = os.environ.get("SEAWEEDFS_TPU_META_LOG_FSYNC", "1") != "0"

# a listener that raises this many times IN A ROW is unsubscribed: a
# permanently broken notification sink must not be re-invoked (and
# re-logged) on every metadata mutation forever
LISTENER_MAX_FAILURES = int(os.environ.get(
    "SEAWEEDFS_TPU_META_LISTENER_MAX_FAILURES", "8"))


# -- geo (hybrid-logical-clock) stamps -------------------------------------
# every mutation on a geo-enabled filer stamps the entry's extended map
# with (hlc_ns, origin_cluster_id); the apply side compares stamps for
# last-writer-wins.  Deletes leave a tombstone in the store KV so a
# late-arriving older create cannot resurrect a deleted object.

GEO_HLC_KEY = "geo.hlc"
_HLC = struct.Struct(">qI")
TOMBSTONE_PREFIX = b"GeoT"


def encode_hlc(ts_ns: int, cluster_id: int) -> bytes:
    return _HLC.pack(ts_ns, cluster_id)


def decode_hlc(raw: bytes | None) -> tuple[int, int] | None:
    """-> (ts_ns, cluster_id) or None for a missing/malformed stamp."""
    if not raw or len(raw) != _HLC.size:
        return None
    return _HLC.unpack(raw)


def entry_hlc(entry) -> tuple[int, int] | None:
    """The LWW stamp of an entry: its geo stamp when present, else its
    mtime promoted to ns with cluster id 0 (pre-geo entries still order,
    coarsely, against geo writes)."""
    if entry is None:
        return None
    stamp = decode_hlc(bytes(entry.extended.get(GEO_HLC_KEY, b"")))
    if stamp is not None:
        return stamp
    mtime = entry.attributes.mtime or entry.attributes.crtime
    return (mtime * 1_000_000_000, 0) if mtime else None


def tombstone_key(path: str) -> bytes:
    return TOMBSTONE_PREFIX + path.encode()


class MetaLogGap(Exception):
    """The requested resume point predates the oldest retained event —
    the consumer must bootstrap from a namespace snapshot instead."""

    def __init__(self, requested_seq: int, first_retained_seq: int):
        super().__init__(
            f"meta log gap: events after seq {requested_seq} requested, "
            f"but retention starts at seq {first_retained_seq}")
        self.requested_seq = requested_seq
        self.first_retained_seq = first_retained_seq


class _Segment:
    __slots__ = ("path", "first_seq", "size", "max_ts")

    def __init__(self, path: str, first_seq: int, size: int):
        self.path = path
        self.first_seq = first_seq
        self.size = size
        # newest ts_ns in the segment, cached by the first full scan of
        # a SEALED segment (immutable thereafter) so later ts-filtered
        # cold reads skip the whole file without I/O
        self.max_ts: int | None = None


def _seg_path(directory: str, first_seq: int) -> str:
    return os.path.join(directory,
                        f"{_SEG_PREFIX}{first_seq:016x}{_SEG_SUFFIX}")


def _fsync_dir(directory: str) -> None:
    """Make a just-created file's directory entry durable (Linux: fsync
    on the dir fd); best-effort on platforms that refuse dir fds."""
    try:
        fd = os.open(directory, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _iter_segment(path: str):
    """Yield (seq, ts_ns, payload) from one segment; a torn tail (short
    header/payload, crc mismatch) ends iteration cleanly — later records
    cannot exist past a torn write in an append-only file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(_REC_HEADER.size)
            if len(header) < _REC_HEADER.size:
                return
            crc, length, seq, ts_ns = _REC_HEADER.unpack(header)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                return
            yield seq, ts_ns, payload


class MetaLogBuffer:
    def __init__(self, capacity: int = 1 << 16, dir: str | None = None,
                 segment_bytes: int = SEGMENT_BYTES,
                 retain_bytes: int = RETAIN_BYTES,
                 fsync: bool | None = None):
        # (arrival_seq, event): the cursor protocol tracks ARRIVAL order,
        # not ts_ns — an aggregated peer event can arrive late with an
        # older timestamp and must still reach live subscribers exactly
        # once (ts_ns stays the cross-filer resume key in since_ns)
        self._events: deque = deque(maxlen=capacity)
        self._cond = threading.Condition()
        self._last_ts = 0
        self._seq = 0
        self._listeners: list = []
        self._listener_failures: dict = {}  # id(fn) -> consecutive count
        # events before this instant (process start) or evicted from the
        # bounded deque are gone UNLESS the durable layer retains them;
        # subscribers asking for older history than either can serve
        # must bootstrap from a store snapshot instead
        self._created_ts = time.time_ns()
        self._evicted_ts = 0
        # -- durable layer -------------------------------------------------
        self._dir = dir
        self._segment_bytes = segment_bytes
        self._retain_bytes = retain_bytes
        self._fsync = FSYNC if fsync is None else fsync
        self._segments: list[_Segment] = []
        self._fh = None  # open handle on the newest segment
        self.first_retained_seq = 1  # seq of the oldest durable record
        # incarnation id: checkpoints taken against one log must never
        # be interpreted against another (a wiped/repointed dir restarts
        # seq at 1 — a consumer resuming by bare seq would silently skip
        # the new incarnation's first N events once last_seq catches up)
        self.log_id = f"mem-{os.urandom(8).hex()}"
        if dir:
            os.makedirs(dir, exist_ok=True)
            id_path = os.path.join(dir, "log.id")
            try:
                with open(id_path, encoding="ascii") as f:
                    self.log_id = f.read().strip()
            except FileNotFoundError:
                self.log_id = os.urandom(8).hex()
                with open(id_path, "w", encoding="ascii") as f:
                    f.write(self.log_id)
                    f.flush()
                    os.fsync(f.fileno())
                _fsync_dir(dir)
            self._recover()

    # -- durable layer -----------------------------------------------------

    def _recover(self) -> None:
        """Rebuild segment metadata, resume seq/ts, truncate a torn tail."""
        names = sorted(n for n in os.listdir(self._dir)
                       if n.startswith(_SEG_PREFIX)
                       and n.endswith(_SEG_SUFFIX))
        for name in names:
            path = os.path.join(self._dir, name)
            first_seq = int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)], 16)
            self._segments.append(
                _Segment(path, first_seq, os.path.getsize(path)))
        if not self._segments:
            return
        self.first_retained_seq = self._segments[0].first_seq
        # walk the LAST segment to find the true end (and the torn tail)
        last = self._segments[-1]
        good_end = 0
        for seq, ts_ns, payload in _iter_segment(last.path):
            self._seq = seq
            self._last_ts = max(self._last_ts, ts_ns)
            good_end += _REC_HEADER.size + len(payload)
        # the clock must resume past the max ts EVER issued, which is
        # not necessarily in the newest segment: aggregator-ingested
        # peer events with OLDER stamps can fill whole segments after a
        # local append with a newer one, and a regressed clock issues
        # stamps that lose LWW remotely to the very entries they
        # overwrote locally.  Retention bounds this walk; the per-seg
        # max doubles as the sealed segments' ts-skip cache, so fresh
        # near-head subscribers don't re-read the whole retained log
        for seg in self._segments[:-1]:
            seg_max = 0
            for _seq, ts_ns, _payload in _iter_segment(seg.path):
                seg_max = max(seg_max, ts_ns)
            if seg_max:
                seg.max_ts = seg_max
            self._last_ts = max(self._last_ts, seg_max)
        if good_end < last.size:
            glog.warning("meta log: truncating torn tail of %s "
                         "(%d -> %d bytes)", last.path, last.size, good_end)
            with open(last.path, "r+b") as f:
                f.truncate(good_end)
            last.size = good_end
        if self._seq == 0:
            # newest segment entirely torn (or empty): its name carries
            # the first seq it would have held
            self._seq = last.first_seq - 1
        if self._seq:
            glog.info("meta log: recovered %d segment(s), seq=%d",
                      len(self._segments), self._seq)

    def _persist_locked(self, seq: int, resp) -> None:
        if not self._dir:
            return
        payload = resp.SerializeToString()
        if self._fh is None or (
                self._segments
                and self._segments[-1].size >= self._segment_bytes):
            self._roll_locked(seq)
        rec = _REC_HEADER.pack(zlib.crc32(payload), len(payload), seq,
                               resp.ts_ns) + payload
        self._fh.write(rec)
        self._fh.flush()
        if self._fsync:
            os.fsync(self._fh.fileno())
        self._segments[-1].size += len(rec)

    def _roll_locked(self, first_seq: int) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        path = _seg_path(self._dir, first_seq)
        self._fh = open(path, "ab")
        if self._fsync:
            # the DIRECTORY entry must be durable too: per-record fsync
            # is useless if power loss drops the whole segment file —
            # recovery would then reissue seqs under the SAME log id and
            # remote (src, log, seq) watermarks would swallow the fresh
            # post-restart events as duplicates
            _fsync_dir(self._dir)
        if not self._segments or self._segments[-1].path != path:
            self._segments.append(
                _Segment(path, first_seq, os.path.getsize(path)))
        self._enforce_retention_locked()

    def _enforce_retention_locked(self) -> None:
        total = sum(s.size for s in self._segments)
        while len(self._segments) > 1 and total > self._retain_bytes:
            victim = self._segments.pop(0)
            total -= victim.size
            try:
                os.remove(victim.path)
            except OSError:
                pass
            self.first_retained_seq = self._segments[0].first_seq

    def close(self) -> None:
        with self._cond:
            if self._fh is not None:
                self._fh.flush()
                if self._fsync:
                    os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None

    def durable(self) -> bool:
        return self._dir is not None

    def last_seq(self) -> int:
        with self._cond:
            return self._seq

    def history_start_ns(self) -> int:
        """Oldest timestamp this log can still replay faithfully."""
        if self._dir and self._segments:
            try:
                for _seq, ts_ns, _payload in _iter_segment(
                        self._segments[0].path):
                    return ts_ns
            except FileNotFoundError:  # retention raced us
                pass
        return max(self._created_ts, self._evicted_ts)

    # -- hybrid logical clock ----------------------------------------------

    def next_ts(self) -> int:
        """Advance and return the HLC: callers stamping entries BEFORE the
        store write (geo LWW) pass the result back into ``append(ts=)``
        so the event and the stored stamp agree."""
        with self._cond:
            ts = time.time_ns()
            if ts <= self._last_ts:
                ts = self._last_ts + 1
            self._last_ts = ts
            return ts

    def observe(self, ts_ns: int) -> None:
        """Fold a REMOTE timestamp into the clock: after applying a
        remote event stamped ts, every later local write must stamp
        strictly greater — the hybrid-logical-clock merge rule."""
        with self._cond:
            self._last_ts = max(self._last_ts, ts_ns)

    # -- append / ingest ----------------------------------------------------

    def append(self, directory: str,
               old_entry: filer_pb2.Entry | None,
               new_entry: filer_pb2.Entry | None,
               delete_chunks: bool = False,
               new_parent_path: str = "",
               signatures: list[int] | None = None,
               ts: int | None = None) -> int:
        event = filer_pb2.EventNotification(
            delete_chunks=delete_chunks,
            new_parent_path=new_parent_path,
            signatures=signatures or [],
        )
        if old_entry is not None:
            event.old_entry.CopyFrom(old_entry)
        if new_entry is not None:
            event.new_entry.CopyFrom(new_entry)
        with self._cond:
            if ts is None:
                ts = time.time_ns()
                if ts <= self._last_ts:  # keep ts strictly monotonic
                    ts = self._last_ts + 1
            elif ts < self._last_ts:
                # the caller reserved this stamp via next_ts() BEFORE
                # taking this lock, and a later reservation appended
                # first: log at a monotonic ts anyway — a ts-resumed
                # subscriber must never see the log regress (it would
                # silently skip this event on resubscribe).  The stored
                # ENTRY keeps the reserved stamp; LWW compares entry
                # stamps, never the event ts (geo ships re-derive from
                # the entry/tombstone).
                ts = self._last_ts + 1
            self._last_ts = max(self._last_ts, ts)
            resp = filer_pb2.SubscribeMetadataResponse(
                directory=directory, ts_ns=ts
            )
            resp.event_notification.CopyFrom(event)
            self._seq += 1
            self._persist_locked(self._seq, resp)
            if len(self._events) == self._events.maxlen:
                self._evicted_ts = max(self._evicted_ts,
                                       self._events[0][1].ts_ns)
            self._events.append((self._seq, resp))
            self._cond.notify_all()
            self._notify_listeners_locked(resp)
        return ts

    def ingest(self, resp: filer_pb2.SubscribeMetadataResponse) -> None:
        """Insert an event from another filer AS-IS (aggregation path):
        the original ts_ns is the cross-cluster ordering key, so it must
        not be re-stamped."""
        with self._cond:
            self._seq += 1
            self._persist_locked(self._seq, resp)
            self._events.append((self._seq, resp))
            self._last_ts = max(self._last_ts, resp.ts_ns)
            self._cond.notify_all()
            self._notify_listeners_locked(resp)

    # -- listeners ----------------------------------------------------------

    def _notify_listeners_locked(self, resp) -> None:
        from ..stats.metrics import META_LISTENER_ERRORS

        dead = []
        for fn in self._listeners:
            try:
                fn(resp)
            except Exception as e:  # a dead notification sink must
                # not kill the write path, but must be visible
                META_LISTENER_ERRORS.labels("error").inc()
                fails = self._listener_failures.get(id(fn), 0) + 1
                self._listener_failures[id(fn)] = fails
                if fails >= LISTENER_MAX_FAILURES:
                    dead.append(fn)
                    glog.error(
                        "meta listener failed %d times in a row; "
                        "unsubscribing it: %s", fails, e)
                else:
                    glog.warning("meta listener failed: %s", e)
            else:
                self._listener_failures.pop(id(fn), None)
        for fn in dead:
            META_LISTENER_ERRORS.labels("evicted").inc()
            self._listeners.remove(fn)
            self._listener_failures.pop(id(fn), None)

    def add_listener(self, fn) -> None:
        """Synchronous callback per event (notification sinks)."""
        with self._cond:
            self._listeners.append(fn)

    def listener_count(self) -> int:
        with self._cond:
            return len(self._listeners)

    # -- reading ------------------------------------------------------------

    def _read_persisted(self, after_seq: int, before_seq: int,
                        min_ts: int = 0):
        """Yield (seq, resp) with after_seq < seq < before_seq from the
        durable segments.  Caller must have verified after_seq+1 >=
        first_retained_seq (else the stream would silently gap).
        ``min_ts`` drops records with ts_ns <= min_ts BEFORE protobuf
        decode (the frame header carries ts) — a subscriber resuming
        near the head must not pay a full-log deserialization."""
        if not self._dir:
            return
        with self._cond:
            segments = list(self._segments)
        for i, seg in enumerate(segments):
            nxt = (segments[i + 1].first_seq
                   if i + 1 < len(segments) else 1 << 62)
            if nxt <= after_seq + 1:
                continue
            if seg.max_ts is not None and seg.max_ts <= min_ts:
                continue  # whole segment predates the subscription
            sealed = i + 1 < len(segments)
            seen_max = 0
            try:
                for seq, ts, payload in _iter_segment(seg.path):
                    seen_max = max(seen_max, ts)
                    if seq >= before_seq:
                        return
                    if seq <= after_seq or ts <= min_ts:
                        continue
                    resp = \
                        filer_pb2.SubscribeMetadataResponse.FromString(
                            payload)
                    yield seq, resp
            except FileNotFoundError:
                # retention deleted this segment mid-read: surface the
                # documented loud-gap protocol, not a raw IO error
                raise MetaLogGap(after_seq, self.first_retained_seq) \
                    from None
            if sealed and seen_max:
                seg.max_ts = seen_max

    def tail(self, after_seq: int,
             stop_event: threading.Event | None = None,
             poll_interval: float = 0.2):
        """Yield (seq, event) for every event with seq > after_seq —
        persisted history first, then the live ring — until stopped.

        Raises ``MetaLogGap`` when retention already dropped events the
        caller has not seen: the consumer must resync from a snapshot
        rather than silently skip mutations."""
        cursor = after_seq
        while stop_event is None or not stop_event.is_set():
            with self._cond:
                if cursor + 1 < self.first_retained_seq and self._dir:
                    raise MetaLogGap(cursor, self.first_retained_seq)
                mem_first = (self._events[0][0] if self._events
                             else self._seq + 1)
                need_cold = cursor + 1 < mem_first
                batch = ([] if need_cold else
                         [(seq, ev) for seq, ev in self._events
                          if seq > cursor])
                if not need_cold and not batch:
                    self._cond.wait(timeout=poll_interval)
            if need_cold:
                # ring already evicted part of the range: serve the cold
                # span from disk, then re-check the ring
                served = False
                for seq, ev in self._read_persisted(cursor, mem_first):
                    served = True
                    cursor = seq
                    yield seq, ev
                if not served:
                    # memory-only log that evicted (or an impossible hole
                    # in the durable layer): an undetectable gap would be
                    # silent corruption downstream — fail loud
                    raise MetaLogGap(cursor, mem_first)
                continue
            for seq, ev in batch:
                cursor = seq
                yield seq, ev

    def subscribe(self, since_ns: int, path_prefix: str = "",
                  stop_event: threading.Event | None = None,
                  poll_interval: float = 0.2):
        """Yield events with ts_ns > since_ns, then tail until stopped.

        The live cursor advances over arrival sequence numbers, so an
        aggregated event ingested late with an older ts_ns is neither
        skipped nor double-delivered.  With a durable layer, history the
        ring evicted (or that predates this process) is served from the
        segment files first."""
        cursor = 0  # arrival seq of the last yielded event
        while stop_event is None or not stop_event.is_set():
            batch = []
            with self._cond:
                mem_first = (self._events[0][0] if self._events
                             else self._seq + 1)
                # the ring moved past the cursor (initial attach, or
                # eviction while a slow consumer drained): serve the
                # cold span from the durable segments first
                need_cold = self._dir is not None and \
                    cursor + 1 < mem_first
                if not need_cold:
                    for seq, ev in self._events:
                        if seq > cursor and ev.ts_ns > since_ns:
                            batch.append((seq, ev))
                    if not batch:
                        self._cond.wait(timeout=poll_interval)
            if need_cold:
                try:
                    for seq, ev in self._read_persisted(
                            cursor, mem_first, min_ts=since_ns):
                        cursor = seq
                        if not path_prefix or _matches_prefix(
                                ev, path_prefix):
                            yield ev
                except MetaLogGap:
                    # retention outran this consumer: subscribe keeps
                    # the ts-protocol's lossy-bootstrap contract (the
                    # caller resumes from a store snapshot); the
                    # seq-exact tail() is the loud-gap surface
                    pass
                # everything below mem_first was scanned (matched,
                # ts-filtered at the frame header, or dropped by
                # retention): resume from the ring
                cursor = max(cursor, mem_first - 1)
                continue
            for seq, ev in batch:
                cursor = seq
                if path_prefix and not _matches_prefix(ev, path_prefix):
                    continue
                yield ev


def _matches_prefix(ev, prefix: str) -> bool:
    """An event is relevant when any affected full path lives under the
    prefix (directory + entry name, old or new)."""
    base = ev.directory.rstrip("/")
    n = ev.event_notification
    for entry in (n.old_entry, n.new_entry):
        if entry.name:
            full = f"{base}/{entry.name}"
            if full.startswith(prefix) or prefix.startswith(full + "/"):
                return True
    if n.new_parent_path and n.new_parent_path.startswith(prefix):
        return True
    return False
