"""Filer metadata event log: in-memory buffer + tailing subscriptions.

Reference: weed/filer/filer_notify.go + weed/util/log_buffer — every
mutation appends an EventNotification with a monotonic ts_ns; subscribers
replay events since a timestamp, then tail live.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..pb import filer_pb2

from ..util import glog


class MetaLogBuffer:
    def __init__(self, capacity: int = 1 << 16):
        # (arrival_seq, event): the cursor protocol tracks ARRIVAL order,
        # not ts_ns — an aggregated peer event can arrive late with an
        # older timestamp and must still reach live subscribers exactly
        # once (ts_ns stays the cross-filer resume key in since_ns)
        self._events: deque = deque(maxlen=capacity)
        self._cond = threading.Condition()
        self._last_ts = 0
        self._seq = 0
        self._listeners: list = []
        # events before this instant (process start) or evicted from the
        # bounded deque are gone; subscribers asking for older history
        # must bootstrap from a store snapshot instead
        self._created_ts = time.time_ns()
        self._evicted_ts = 0

    def history_start_ns(self) -> int:
        """Oldest timestamp this buffer can still replay faithfully."""
        return max(self._created_ts, self._evicted_ts)

    def append(self, directory: str,
               old_entry: filer_pb2.Entry | None,
               new_entry: filer_pb2.Entry | None,
               delete_chunks: bool = False,
               new_parent_path: str = "",
               signatures: list[int] | None = None) -> int:
        event = filer_pb2.EventNotification(
            delete_chunks=delete_chunks,
            new_parent_path=new_parent_path,
            signatures=signatures or [],
        )
        if old_entry is not None:
            event.old_entry.CopyFrom(old_entry)
        if new_entry is not None:
            event.new_entry.CopyFrom(new_entry)
        with self._cond:
            ts = time.time_ns()
            if ts <= self._last_ts:  # keep ts strictly monotonic
                ts = self._last_ts + 1
            self._last_ts = ts
            resp = filer_pb2.SubscribeMetadataResponse(
                directory=directory, ts_ns=ts
            )
            resp.event_notification.CopyFrom(event)
            self._seq += 1
            if len(self._events) == self._events.maxlen:
                self._evicted_ts = self._events[0][1].ts_ns
            self._events.append((self._seq, resp))
            self._cond.notify_all()
            for fn in self._listeners:
                try:
                    fn(resp)
                except Exception as e:  # a dead notification sink must
                    # not kill the write path, but must be visible
                    glog.warning("meta listener failed: %s", e)
        return ts

    def ingest(self, resp: filer_pb2.SubscribeMetadataResponse) -> None:
        """Insert an event from another filer AS-IS (aggregation path):
        the original ts_ns is the cross-cluster ordering key, so it must
        not be re-stamped."""
        with self._cond:
            self._seq += 1
            self._events.append((self._seq, resp))
            self._last_ts = max(self._last_ts, resp.ts_ns)
            self._cond.notify_all()
            for fn in self._listeners:
                try:
                    fn(resp)
                except Exception as e:
                    glog.warning("meta listener failed: %s", e)

    def add_listener(self, fn) -> None:
        """Synchronous callback per event (notification sinks)."""
        with self._cond:
            self._listeners.append(fn)

    def subscribe(self, since_ns: int, path_prefix: str = "",
                  stop_event: threading.Event | None = None,
                  poll_interval: float = 0.2):
        """Yield events with ts_ns > since_ns, then tail until stopped.

        The live cursor advances over arrival sequence numbers, so an
        aggregated event ingested late with an older ts_ns is neither
        skipped nor double-delivered."""
        cursor = 0  # arrival seq of the last yielded event
        while stop_event is None or not stop_event.is_set():
            batch = []
            with self._cond:
                for seq, ev in self._events:
                    if seq > cursor and ev.ts_ns > since_ns:
                        batch.append((seq, ev))
                if not batch:
                    self._cond.wait(timeout=poll_interval)
            for seq, ev in batch:
                cursor = seq
                if path_prefix and not _matches_prefix(ev, path_prefix):
                    continue
                yield ev


def _matches_prefix(ev, prefix: str) -> bool:
    """An event is relevant when any affected full path lives under the
    prefix (directory + entry name, old or new)."""
    base = ev.directory.rstrip("/")
    n = ev.event_notification
    for entry in (n.old_entry, n.new_entry):
        if entry.name:
            full = f"{base}/{entry.name}"
            if full.startswith(prefix) or prefix.startswith(full + "/"):
                return True
    if n.new_parent_path and n.new_parent_path.startswith(prefix):
        return True
    return False
