"""Filer core: path namespace over a FilerStore, with chunk lifecycle.

Reference: weed/filer/filer.go:30-45 plus filer_delete_entry.go /
filer_deletion.go (recursive delete + async blob deletion queue) and
filer_notify.go (metadata event log).  Paths are absolute ("/a/b/c");
an entry lives at (directory="/a/b", name="c").  Buckets live under
/buckets/<name> and map to collections.
"""

from __future__ import annotations

import os
import queue
import threading
import time

from ..pb import filer_pb2
from ..util import faultpoint, glog
from . import filechunks
from .filerstore import FilerStore
from .fleet.tenant import tenant_for_path
from .meta_log import (
    GEO_HLC_KEY,
    MetaLogBuffer,
    decode_hlc,
    encode_hlc,
    tombstone_key,
)

ROOT = "/"
DIR_BUCKETS = "/buckets"

FP_STORE_INSERT = faultpoint.register("filer.store.insert")


def _entry_bytes(entry: filer_pb2.Entry) -> int:
    """Logical size of a file entry for tenant accounting."""
    return (filechunks.total_size(entry.chunks)
            or entry.attributes.file_size or len(entry.content))


def split_path(path: str) -> tuple[str, str]:
    path = "/" + path.strip("/")
    if path == "/":
        return "/", ""
    directory, name = path.rsplit("/", 1)
    return directory or "/", name


def join_path(directory: str, name: str) -> str:
    if not name:
        return directory
    return (directory.rstrip("/") or "") + "/" + name


class Filer:
    def __init__(self, store: FilerStore, delete_chunks_fn=None,
                 resolve_chunks_fn=None, meta_log_dir: str | None = None,
                 meta_log_fsync: bool | None = None):
        """``delete_chunks_fn(file_ids: list[str])`` deletes blobs; when
        None, chunk deletion is a no-op (offline/metadata-only use).

        ``resolve_chunks_fn(chunks) -> chunks`` expands manifest chunks;
        garbage-collection diffs run over EXPANDED lists on both sides so
        a chunk folded into a manifest is never mistaken for garbage
        (reference: MinusChunks with a lookup fn, filechunk_manifest.go).

        ``meta_log_dir`` makes the metadata event log durable (fsynced
        segment files, monotonic gap-detectable sequence numbers) — the
        substrate the geo replication plane tails (ISSUE 12).
        """
        self.store = store
        self.meta_log = MetaLogBuffer(dir=meta_log_dir,
                                      fsync=meta_log_fsync)
        # striped per-path locks serializing every stamped mutation of
        # one path against the geo applier's LWW check-then-write
        # (replication/geo.py): without them a concurrent newer local
        # write landing between the applier's stamp read and its store
        # write would be silently overwritten by an older remote event
        self._path_locks = [threading.RLock()
                            for _ in range(256)]  # power of two: masked
        # geo plane: when enabled, every mutation stamps the entry with a
        # hybrid-logical-clock (ts_ns, cluster_id) pair and deletes leave
        # tombstones, so active-active peers can resolve last-writer-wins
        self.cluster_id = 0
        self.geo_stamp = False
        # fleet.TenantManager when the sharded metadata plane is on:
        # quota checks + usage accounting run HERE, in the local
        # mutation path only — meta_aggregator replays write straight to
        # the store, so each tenant is accounted exactly once fleet-wide
        # (on the shard that owns its bucket)
        self.tenants = None
        self._append_lock = threading.Lock()
        # serializes hardlink KV counter read-modify-writes: two
        # concurrent unlinks must not both read counter=2/write 1 and
        # leak the shared chunks forever
        self._hardlink_lock = threading.Lock()
        self._delete_fn = delete_chunks_fn
        self._resolve_fn = resolve_chunks_fn
        self._deletion_q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._deleter = threading.Thread(target=self._deletion_loop, daemon=True)
        self._deleter.start()

    def close(self) -> None:
        self._stop.set()
        self._deletion_q.put(None)
        self.meta_log.close()
        self.store.close()

    # -- hardlinks (filerstore_hardlink.go:12-40) --------------------------
    #
    # A hardlinked file's shared truth (attributes + chunks + counter)
    # lives in the store's KV space keyed by the 17-byte hard_link_id;
    # directory entries are stubs carrying the id.  Reads merge the KV
    # meta back in; unlink decrements the counter and reclaims the data
    # chunks only when the LAST link dies.

    @staticmethod
    def _encode_hardlink_meta(entry: filer_pb2.Entry) -> bytes:
        meta = filer_pb2.Entry(
            hard_link_id=entry.hard_link_id,
            hard_link_counter=entry.hard_link_counter,
        )
        meta.attributes.CopyFrom(entry.attributes)
        meta.chunks.extend(entry.chunks)
        for k, v in entry.extended.items():
            meta.extended[k] = v
        return meta.SerializeToString()

    def _set_hardlink(self, entry: filer_pb2.Entry) -> None:
        if entry.hard_link_id:
            with self._hardlink_lock:
                self.store.kv_put(bytes(entry.hard_link_id),
                                  self._encode_hardlink_meta(entry))

    def _maybe_read_hardlink(
        self, entry: filer_pb2.Entry | None
    ) -> filer_pb2.Entry | None:
        if entry is None or not entry.hard_link_id:
            return entry
        blob = self.store.kv_get(bytes(entry.hard_link_id))
        if not blob:
            return entry  # dangling link: serve the stub as-is
        meta = filer_pb2.Entry.FromString(blob)
        entry.attributes.CopyFrom(meta.attributes)
        del entry.chunks[:]
        entry.chunks.extend(meta.chunks)
        entry.hard_link_counter = meta.hard_link_counter
        for k, v in meta.extended.items():
            entry.extended[k] = v
        return entry

    def _delete_hardlink(self, hard_link_id: bytes,
                         is_delete_data: bool) -> None:
        """Decrement the link counter; on the last unlink drop the KV meta
        and reclaim the shared chunks (the per-entry stub's chunk list is
        never trusted for deletion — the KV meta is the owner)."""
        key = bytes(hard_link_id)
        with self._hardlink_lock:
            blob = self.store.kv_get(key)
            if not blob:
                return
            meta = filer_pb2.Entry.FromString(blob)
            meta.hard_link_counter -= 1
            if meta.hard_link_counter <= 0:
                if is_delete_data and meta.chunks:
                    self.queue_chunk_deletion(self._all_fids(meta.chunks))
                self.store.kv_delete(key)
                return
            self.store.kv_put(key, meta.SerializeToString())

    # -- geo stamping ------------------------------------------------------

    def _stripe_index(self, path: str) -> int:
        return hash(path) & (len(self._path_locks) - 1)

    def path_mutation_lock(self, path: str) -> threading.RLock:
        """The stripe lock covering ``path``: reentrant, so the geo
        applier can hold it across its LWW check + write-through while
        create/delete below re-acquire it."""
        return self._path_locks[self._stripe_index(path)]

    def _geo_ts(self, entry: filer_pb2.Entry | None = None,
                relay: bool = False) -> int | None:
        """HLC-stamp a mutation (geo mode only): stamps ``entry``'s
        extended map and returns the clock value so the metadata event
        carries the SAME ts as the stored stamp.  A RELAY (``relay=``:
        the mutation carries replication signatures — geo applies,
        within-cluster sink/aggregator writes) keeps an existing stamp:
        LWW must compare origin write time, not relay time — it returns
        None so the EVENT still stamps fresh and monotonic.  A direct
        client mutation that happens to echo a stored stamp back (a
        read-modify-write UpdateEntry: chmod, touch) is a NEW write and
        is re-stamped — honoring the echoed stamp would make the update
        compare equal to the overwritten version everywhere and never
        replicate."""
        if not self.geo_stamp:
            return None
        if entry is not None and GEO_HLC_KEY in entry.extended:
            stamp = decode_hlc(bytes(entry.extended[GEO_HLC_KEY]))
            if relay and stamp is not None:
                self.meta_log.observe(stamp[0])
                return None
            del entry.extended[GEO_HLC_KEY]
        ts = self.meta_log.next_ts()
        if entry is not None:
            entry.extended[GEO_HLC_KEY] = encode_hlc(ts, self.cluster_id)
        return ts

    # -- create/update -----------------------------------------------------

    def create_entry(self, directory: str, entry: filer_pb2.Entry,
                     o_excl: bool = False, signatures=None) -> None:
        with self.path_mutation_lock(join_path(directory, entry.name)):
            self._create_entry_locked(directory, entry, o_excl,
                                      signatures)

    def _create_entry_locked(self, directory: str,
                             entry: filer_pb2.Entry,
                             o_excl: bool = False,
                             signatures=None) -> None:
        # read the old entry MERGED so a hardlinked file's true (shared)
        # chunk list is what the rewrite diff below runs against —
        # diffing the stub would leak every shadowed chunk forever
        old = self._maybe_read_hardlink(
            self.store.find_entry(directory, entry.name))
        if old is not None and o_excl:
            raise FileExistsError(join_path(directory, entry.name))
        self._ensure_parents(directory, signatures=signatures)
        if not entry.attributes.crtime:
            entry.attributes.crtime = int(time.time())
        if not entry.attributes.mtime:
            entry.attributes.mtime = int(time.time())
        # quota gate BEFORE any mutation: a rejection must leave the
        # store (including hardlink KV counters) untouched
        tenant, d_objects, d_bytes = self._tenant_delta(
            directory, entry, old)
        geo_ts = self._geo_ts(entry, relay=bool(signatures))
        self._set_hardlink(entry)
        broke_link = (old is not None and old.hard_link_id
                      and old.hard_link_id != entry.hard_link_id)
        if broke_link:
            # overwrite breaks the old link (handleUpdateToHardLinks);
            # the counter logic owns the shared chunks' lifetime here —
            # other links may still reference them, so no rewrite diff
            self._delete_hardlink(old.hard_link_id, is_delete_data=True)
        faultpoint.inject(FP_STORE_INSERT,
                          ctx=join_path(directory, entry.name))
        self.store.insert_entry(directory, entry)
        if tenant:
            self.tenants.record(tenant, d_objects, d_bytes)
        # blobs shadowed by the rewrite get deleted asynchronously; runs
        # for plain entries AND for a hardlinked entry rewritten in place
        # (same id: every link now sees the new chunks via the KV meta)
        if not broke_link and old is not None and old.chunks:
            self.queue_chunk_deletion(
                self._garbage_fids(old.chunks, entry.chunks)
            )
        self.meta_log.append(directory, old, entry, signatures=signatures,
                             ts=geo_ts)

    def update_entry(self, directory: str, entry: filer_pb2.Entry,
                     signatures=None) -> None:
        with self.path_mutation_lock(join_path(directory, entry.name)):
            self._update_entry_locked(directory, entry, signatures)

    def _update_entry_locked(self, directory: str,
                             entry: filer_pb2.Entry,
                             signatures=None) -> None:
        old = self._maybe_read_hardlink(
            self.store.find_entry(directory, entry.name))
        if old is None:
            raise FileNotFoundError(join_path(directory, entry.name))
        tenant, d_objects, d_bytes = self._tenant_delta(
            directory, entry, old)
        geo_ts = self._geo_ts(entry, relay=bool(signatures))
        self._set_hardlink(entry)
        if (old.hard_link_id
                and old.hard_link_id != entry.hard_link_id):
            self._delete_hardlink(old.hard_link_id, is_delete_data=True)
            self.store.update_entry(directory, entry)
        else:
            self.store.update_entry(directory, entry)
            if old.chunks:
                self.queue_chunk_deletion(
                    self._garbage_fids(old.chunks, entry.chunks)
                )
        if tenant:
            self.tenants.record(tenant, d_objects, d_bytes)
        self.meta_log.append(directory, old, entry, signatures=signatures,
                             ts=geo_ts)

    def _tenant_delta(self, directory: str, entry: filer_pb2.Entry,
                      old: filer_pb2.Entry | None) -> tuple[str, int, int]:
        """-> (tenant, d_objects, d_bytes) for writing ``entry`` over
        ``old``, AFTER passing the quota gate (raises QuotaExceededError
        when the delta would overflow the tenant's bounds).  Directories
        carry no usage; untenanted paths return ("", 0, 0)."""
        if self.tenants is None or entry.is_directory:
            return "", 0, 0
        tenant = tenant_for_path(join_path(directory, entry.name))
        if not tenant:
            return "", 0, 0
        old_is_file = old is not None and not old.is_directory
        d_objects = 0 if old_is_file else 1
        d_bytes = _entry_bytes(entry) - (
            _entry_bytes(old) if old_is_file else 0)
        self.tenants.check_quota(tenant, d_objects, d_bytes)
        return tenant, d_objects, d_bytes

    def _garbage_fids(self, old_chunks, new_chunks) -> list[str]:
        """fids in old but not new, with manifests EXPANDED on both sides
        so a chunk folded into a manifest is never mistaken for garbage.
        A resolution failure skips collection (leak beats corruption)."""
        try:
            garbage = filechunks.minus_chunks(
                self._expanded(old_chunks), self._expanded(new_chunks)
            )
        except Exception:
            glog.warning("manifest unresolvable; skipping GC of a rewrite")
            return []
        return [c.file_id for c in garbage]

    def _expanded(self, chunks) -> list:
        """Chunk list + everything reachable through its manifests."""
        chunks = list(chunks)
        if self._resolve_fn is None or not any(
            c.is_chunk_manifest for c in chunks
        ):
            return chunks
        return chunks + [
            c for c in self._resolve_fn(chunks) if not c.is_chunk_manifest
        ]

    def _all_fids(self, chunks) -> list[str]:
        """Every fid a file's deletion must reclaim: the chunks themselves
        plus everything inside their manifests (resolve-before-delete,
        filer_delete_entry.go).  Unresolvable manifests delete what is
        known rather than failing the metadata removal."""
        try:
            return [c.file_id for c in self._expanded(chunks)]
        except Exception:
            glog.warning("manifest unresolvable; inner chunks may leak")
            return [c.file_id for c in chunks]

    def append_chunks(self, directory: str, name: str, chunks) -> None:
        # serialize the read-modify-write: two concurrent appenders would
        # otherwise both read the same chunk list and one would lose
        # chunks (the path stripe additionally fences the geo applier;
        # lock order append->stripe is safe: no holder of a stripe ever
        # takes the append lock)
        with self._append_lock, \
                self.path_mutation_lock(join_path(directory, name)):
            # merged read: appending to a hardlinked file must extend the
            # SHARED chunk list, not the stub's stale copy
            entry = self._maybe_read_hardlink(
                self.store.find_entry(directory, name))
            existed = entry is not None
            if entry is None:
                self._ensure_parents(directory)
                entry = filer_pb2.Entry(name=name)
                entry.attributes.crtime = int(time.time())
            offset = filechunks.total_size(entry.chunks)
            added = 0
            for c in chunks:
                c2 = filer_pb2.FileChunk()
                c2.CopyFrom(c)
                c2.offset = offset
                offset += c2.size
                added += c2.size
                entry.chunks.append(c2)
            entry.attributes.mtime = int(time.time())
            entry.attributes.file_size = offset
            tenant = ""
            if self.tenants is not None:
                tenant = tenant_for_path(join_path(directory, name))
                if tenant:
                    self.tenants.check_quota(
                        tenant, 0 if existed else 1, added)
            # a geo append is a fresh local write (appends never relay an
            # origin stamp), so drop any stale stamp before re-stamping
            entry.extended.pop(GEO_HLC_KEY, None)
            geo_ts = self._geo_ts(entry)
            self._set_hardlink(entry)
            self.store.insert_entry(directory, entry)
            if tenant:
                self.tenants.record(tenant, 0 if existed else 1, added)
            self.meta_log.append(directory, None, entry, ts=geo_ts)

    def _ensure_parents(self, directory: str, signatures=None,
                        stamp: bytes | None = None) -> None:
        """mkdir -p the ancestor chain (filer.go ensures parent dirs).
        The dir-creation events inherit the mutation's signatures so
        bidirectional sync filters them like the triggering write.

        ``stamp`` (a geo apply relaying a remote mkdir) pins the created
        dirs to the ORIGIN's HLC: without it they would stamp as local
        apply-time, and a backlog-drained delete/rename of the dir —
        carrying the origin's older hlc — would lose LWW to the dir's
        own arrival time and never apply."""
        if directory in ("/", ""):
            return
        parent, name = split_path(directory)
        existing = self.store.find_entry(parent, name)
        if existing is not None:
            return
        self._ensure_parents(parent, signatures=signatures, stamp=stamp)
        d = filer_pb2.Entry(name=name, is_directory=True)
        d.attributes.crtime = int(time.time())
        d.attributes.mtime = d.attributes.crtime
        d.attributes.file_mode = 0o40755  # dir bit
        if stamp:
            d.extended[GEO_HLC_KEY] = stamp
        self.store.insert_entry(parent, d)
        self.meta_log.append(parent, None, d, signatures=signatures)

    # -- read --------------------------------------------------------------

    def find_entry(self, path: str) -> filer_pb2.Entry | None:
        directory, name = split_path(path)
        if name == "":
            root = filer_pb2.Entry(name="/", is_directory=True)
            return root
        return self._maybe_read_hardlink(
            self.store.find_entry(directory, name))

    def list_directory(self, directory: str, start_from: str = "",
                       inclusive: bool = False, prefix: str = "",
                       limit: int = 1024):
        for e in self.store.list_entries(
            directory, start_from, inclusive, prefix, limit
        ):
            yield self._maybe_read_hardlink(e)

    # -- delete ------------------------------------------------------------

    def delete_entry(self, directory: str, name: str,
                     is_recursive: bool = False,
                     ignore_recursive_error: bool = False,
                     is_delete_data: bool = True,
                     signatures=None,
                     tombstone: bytes | None = None) -> None:
        with self.path_mutation_lock(join_path(directory, name)):
            self._delete_entry_locked(
                directory, name, is_recursive, ignore_recursive_error,
                is_delete_data, signatures, tombstone)

    def _delete_entry_locked(self, directory: str, name: str,
                             is_recursive: bool = False,
                             ignore_recursive_error: bool = False,
                             is_delete_data: bool = True,
                             signatures=None,
                             tombstone: bytes | None = None) -> None:
        entry = self.store.find_entry(directory, name)
        if entry is None:
            raise FileNotFoundError(join_path(directory, name))
        if entry.is_directory:
            path = join_path(directory, name)
            children = list(self.store.list_entries(path, limit=2))
            if children and not is_recursive:
                raise IsADirectoryError(f"{path} is not empty")
            try:
                self._delete_tree(path, is_delete_data)
            except Exception:
                if not ignore_recursive_error:
                    raise
        elif entry.hard_link_id:
            # unlink: the KV meta owns the shared chunks' lifetime
            self._delete_hardlink(entry.hard_link_id, is_delete_data)
        elif is_delete_data and entry.chunks:
            self.queue_chunk_deletion(self._all_fids(entry.chunks))
        geo_ts = None
        if self.geo_stamp:
            # tombstone: a late-arriving older geo create must not
            # resurrect this path (replication/geo.py LWW compare).  A
            # relay (geo apply) passes ``tombstone=`` carrying the
            # ORIGIN's stamp: it must be in the KV BEFORE the event is
            # appended below, or a tailing replicator relaying the
            # delete onward could read a fresh local stamp and inflate
            # the fence around a 3+-cluster mesh
            if tombstone is None:
                geo_ts = self.meta_log.next_ts()
                tombstone = encode_hlc(geo_ts, self.cluster_id)
            self.store.kv_put(tombstone_key(join_path(directory, name)),
                              tombstone)
        self.store.delete_entry(directory, name)
        if self.tenants is not None and not entry.is_directory:
            tenant = tenant_for_path(join_path(directory, name))
            if tenant:
                self.tenants.record(tenant, -1, -_entry_bytes(entry))
        self.meta_log.append(
            directory, entry, None, delete_chunks=is_delete_data,
            signatures=signatures, ts=geo_ts,
        )

    def _delete_tree(self, path: str, is_delete_data: bool) -> None:
        """Collect chunk fids of the whole subtree, then drop the metadata."""
        tenant = (tenant_for_path(path)
                  if self.tenants is not None else "")
        stack = [path]
        while stack:
            d = stack.pop()
            start = ""
            while True:
                batch = list(self.store.list_entries(d, start_from=start, limit=1024))
                if not batch:
                    break
                for e in batch:
                    if e.is_directory:
                        stack.append(join_path(d, e.name))
                    elif e.hard_link_id:
                        self._delete_hardlink(e.hard_link_id, is_delete_data)
                    elif is_delete_data and e.chunks:
                        self.queue_chunk_deletion(self._all_fids(e.chunks))
                    if tenant and not e.is_directory:
                        self.tenants.record(tenant, -1, -_entry_bytes(e))
                start = batch[-1].name
        self.store.delete_folder_children(path)

    # -- rename ------------------------------------------------------------

    def rename_entry(self, old_dir: str, old_name: str,
                     new_dir: str, new_name: str) -> None:
        """AtomicRenameEntry (filer_grpc_server_rename.go): move the entry
        and, for directories, re-root all children."""
        # both endpoint stripes, in index order (deadlock-free vs a
        # concurrent rename crossing the same pair the other way)
        stripes = sorted({
            self._stripe_index(join_path(old_dir, old_name)),
            self._stripe_index(join_path(new_dir, new_name))})
        for i in stripes:
            self._path_locks[i].acquire()
        try:
            self._rename_entry_locked(old_dir, old_name, new_dir,
                                      new_name)
        finally:
            for i in reversed(stripes):
                self._path_locks[i].release()

    def _rename_entry_locked(self, old_dir: str, old_name: str,
                             new_dir: str, new_name: str) -> None:
        entry = self.store.find_entry(old_dir, old_name)
        if entry is None:
            raise FileNotFoundError(join_path(old_dir, old_name))
        if self.store.find_entry(new_dir, new_name) is not None:
            raise FileExistsError(join_path(new_dir, new_name))
        self._ensure_parents(new_dir)
        moved = filer_pb2.Entry()
        moved.CopyFrom(entry)
        moved.name = new_name
        # a rename is a fresh write at the new path: re-stamp (and
        # tombstone the old path so geo peers don't resurrect it)
        moved.extended.pop(GEO_HLC_KEY, None)
        geo_ts = self._geo_ts(moved)
        if geo_ts is not None:
            self.store.kv_put(tombstone_key(join_path(old_dir, old_name)),
                              encode_hlc(geo_ts, self.cluster_id))
        self.store.insert_entry(new_dir, moved)
        if entry.is_directory:
            old_path = join_path(old_dir, old_name)
            new_path = join_path(new_dir, new_name)
            self._move_children(old_path, new_path)
        self.store.delete_entry(old_dir, old_name)
        if self.tenants is not None and not entry.is_directory:
            # cross-tenant rename moves the usage with the file; renames
            # of whole directories across tenants are not produced by
            # any gateway path and stay advisory
            t_old = tenant_for_path(join_path(old_dir, old_name))
            t_new = tenant_for_path(join_path(new_dir, new_name))
            if t_old != t_new:
                size = _entry_bytes(entry)
                if t_old:
                    self.tenants.record(t_old, -1, -size)
                if t_new:
                    self.tenants.record(t_new, 1, size)
        self.meta_log.append(
            old_dir, entry, moved, new_parent_path=new_dir, ts=geo_ts,
        )

    def _move_children(self, old_path: str, new_path: str) -> None:
        start = ""
        while True:
            batch = list(self.store.list_entries(old_path, start_from=start, limit=1024))
            if not batch:
                break
            for e in batch:
                child = filer_pb2.Entry()
                child.CopyFrom(e)
                self.store.insert_entry(new_path, child)
                if e.is_directory:
                    self._move_children(
                        join_path(old_path, e.name), join_path(new_path, e.name)
                    )
                self.store.delete_entry(old_path, e.name)
            start = batch[-1].name

    # -- buckets / collections --------------------------------------------

    def bucket_collection(self, path: str) -> str:
        """Files under /buckets/<b>/ go to collection <b> (filer.go
        DirBucketsPath convention)."""
        path = "/" + path.strip("/")
        if path.startswith(DIR_BUCKETS + "/"):
            rest = path[len(DIR_BUCKETS) + 1 :]
            return rest.split("/", 1)[0]
        return ""

    def delete_collection_entries(self, collection: str) -> None:
        """Drop /buckets/<collection> metadata (blobs die with the
        collection on the volume servers)."""
        try:
            self.delete_entry(DIR_BUCKETS, collection, is_recursive=True,
                              is_delete_data=False)
        except FileNotFoundError:
            pass

    # -- async blob deletion ----------------------------------------------

    def queue_chunk_deletion(self, file_ids: list[str]) -> None:
        if file_ids:
            self._deletion_q.put(list(file_ids))

    def _deletion_loop(self) -> None:
        while not self._stop.is_set():
            item = self._deletion_q.get()
            if item is None:
                return
            if self._delete_fn is None:
                continue
            try:
                self._delete_fn(item)
            except Exception as e:  # orphaned blobs are an operator
                # problem; losing the error hides them forever
                glog.warning("deferred blob deletion failed: %s", e)

    def drain_deletions(self, timeout: float = 5.0) -> None:
        """Testing hook: wait for queued blob deletions to be processed."""
        deadline = time.monotonic() + timeout
        while not self._deletion_q.empty() and time.monotonic() < deadline:
            time.sleep(0.02)
        time.sleep(0.05)
