"""Filer HTTP data path: auto-chunking writes, chunk-resolved reads, listing.

Reference: weed/server/filer_server_handlers_write_autochunk.go:24 (upload
split into fixed-size chunks, each assigned + uploaded to volume servers,
then one CreateEntry) and filer_server_handlers_read.go (resolve chunk
views, range reads).  Directory GETs return a JSON listing with
pagination (?limit=&lastFileName=).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler
from ..util.httpd import (
    BufferedResponseMixin,
    make_http_server,
    shield_handler,
)

from ..pb import filer_pb2
from ..telemetry import hotkeys, http_request, serve_debug_http, trace
from . import filechunks
from .filer import join_path, split_path
from .fleet.tenant import (
    QuotaExceededError,
    SlowDownError,
    tenant_for_path,
)


class FilerHttpHandler(BufferedResponseMixin, BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "seaweedfs-tpu-filer"

    filer_server = None  # injected by serve_http

    def log_message(self, fmt, *args):
        pass

    @property
    def filer(self):
        return self.filer_server.filer

    def _send(self, code: int, body: bytes = b"",
              content_type: str = "application/json",
              extra: dict | None = None):
        extra = extra or {}
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        if "Content-Length" not in extra:
            self.send_header("Content-Length", str(len(body)))
        for k, v in extra.items():
            self.send_header(k, v)
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _json(self, code: int, obj: dict):
        self._send(code, json.dumps(obj).encode())

    # -- admission (fleet WFQ) ---------------------------------------------

    def _admitted(self, fn) -> None:
        """Run one request under the tenant admission gate.  A rejection
        is a well-formed 503 with Retry-After + a machine-readable
        X-Seaweed-Reject header the S3 gateway translates into SlowDown
        XML; untenanted paths (config, /debug) pass uncounted."""
        tenant = tenant_for_path(
            urllib.parse.unquote(urllib.parse.urlparse(self.path).path))
        hotkeys.record("tenant", tenant)
        try:
            with self.filer_server.admission.admit(tenant):
                fn()
        except SlowDownError as e:
            self._send(503, json.dumps({"error": str(e)}).encode(),
                       extra={"Retry-After": str(e.retry_after),
                              "X-Seaweed-Reject": "slowdown"})

    # -- read / list -------------------------------------------------------

    def do_GET(self):
        with http_request(self, "filer", "get"):
            self._admitted(self._do_get)

    def _do_get(self):
        u = urllib.parse.urlparse(self.path)
        path = urllib.parse.unquote(u.path)
        q = urllib.parse.parse_qs(u.query)
        if path == "/debug/tenants":
            return self._serve_tenants(q)
        if path == "/.geo/status":
            return self._serve_geo_status()
        # debug/observability surface (exact paths, ahead of the namespace)
        if serve_debug_http(self, path):
            return
        entry = self.filer.find_entry(path)
        if entry is None:
            return self._json(404, {"error": f"{path}: not found"})
        if entry.is_directory:
            return self._list_dir(path, q)
        return self._read_file(path, entry)

    def do_HEAD(self):
        with http_request(self, "filer", "get"):
            self._admitted(self._do_get)

    def _serve_tenants(self, q: dict):
        """The shard's tenant plane in one JSON: quota config + usage per
        tenant, the admission controller's live state, and this store's
        entry count (the `filer.ring` shell command's data source).

        ``?set=<tenant>&quota_bytes=&quota_objects=&weight=`` updates a
        tenant's config — the HTTP twin of a gRPC KvPut, which already
        exposes the same store to anyone with cluster reach."""
        fs = self.filer_server
        if q.get("set", [""])[0]:
            tenant = q["set"][0]
            kw = {}
            for key in ("quota_bytes", "quota_objects"):
                if q.get(key, [""])[0]:
                    try:
                        kw[key] = int(q[key][0])
                    except ValueError:
                        return self._json(400, {
                            "error": f"{key} must be an integer"})
            if q.get("weight", [""])[0]:
                try:
                    kw["weight"] = float(q["weight"][0])
                except ValueError:
                    return self._json(400, {"error": "bad weight"})
            conf = fs.tenants.set_config(tenant, **kw)
            return self._json(200, {"tenant": tenant, "config": conf})
        try:
            entries = self.filer.store.count_entries()
        except Exception:  # noqa: BLE001 — optional per-backend
            entries = None
        return self._json(200, {
            "tenants": fs.tenants.snapshot(),
            "admission": fs.admission.snapshot(),
            "entries": entries,
            "store": type(self.filer.store).__name__,
        })

    def _list_dir(self, path: str, q: dict):
        limit = int(q.get("limit", ["100"])[0])
        last = q.get("lastFileName", [""])[0]
        entries = list(
            self.filer.list_directory(
                "/" + path.strip("/") if path != "/" else "/",
                start_from=last,
                limit=limit + 1,
            )
        )
        more = len(entries) > limit
        entries = entries[:limit]
        return self._json(200, {
            "Path": path,
            "Entries": [_entry_json(path, e) for e in entries],
            "Limit": limit,
            "LastFileName": entries[-1].name if entries else "",
            "ShouldDisplayLoadMore": more,
        })

    def _read_file(self, path: str, entry: filer_pb2.Entry):
        mime = entry.attributes.mime or "application/octet-stream"
        size = filechunks.total_size(entry.chunks) or len(entry.content)
        etag = filechunks.etag(entry.chunks) if entry.chunks else ""
        extra = {"Accept-Ranges": "bytes", "Etag": f'"{etag}"'}
        start, length = 0, size
        rng = self.headers.get("Range")
        status = 200
        if rng and rng.startswith("bytes="):
            try:
                start_s, end_s = rng[len("bytes="):].split("-", 1)
                if not start_s:
                    start = max(0, size - int(end_s))
                    end = size - 1
                else:
                    start = int(start_s)
                    end = min(int(end_s), size - 1) if end_s else size - 1
                if start > end:
                    raise ValueError
                length = end - start + 1
                extra["Content-Range"] = f"bytes {start}-{end}/{size}"
                status = 206
            except ValueError:
                return self._json(416, {"error": "bad range"})
        if self.command == "HEAD":
            return self._send(status, b"\0" * 0, mime,
                              {**extra, "Content-Length": str(length)})
        try:
            data = self.filer_server.read_entry_range(entry, start, length)
        except Exception as e:
            # only reached after replica failover AND the refreshed-lookup
            # (EC degraded-read) round both failed; the trace id links the
            # 5xx to the per-location failures in /debug/traces
            return self._json(500, {
                "error": str(e),
                "trace": trace.current_trace_id() or "",
            })
        self._send(status, data, mime, extra)

    # -- geo replication (replication/geo.py) ------------------------------

    def _serve_geo_status(self):
        fs = self.filer_server
        if fs.geo_applier is None:
            return self._json(404, {"error": "geo replication not enabled"})
        return self._json(200, {
            "clusterId": fs.filer.cluster_id,
            "signature": fs.signature,
            "links": [r.status() for r in fs.geo_replicators],
            "applier": fs.geo_applier.status(),
        })

    def _geo_post(self):
        """POST /.geo/apply — one remote-cluster event, LWW-resolved.

        Replication traffic bypasses tenant admission (it is background
        budgeted by the sender's token bucket); quota enforcement still
        runs inside the write path and surfaces as a permanent 403."""
        fs = self.filer_server
        u = urllib.parse.urlparse(self.path)
        if u.path != "/.geo/apply" or fs.geo_applier is None:
            # the posted body goes unread: the connection must not be
            # reused or the next request would parse out of object bytes
            self.close_connection = True
            return self._send(404, json.dumps(
                {"error": "geo replication not enabled"}).encode(),
                extra={"Connection": "close"})
        q = urllib.parse.parse_qs(u.query)

        def qi(name):
            try:
                return int(q.get(name, ["0"])[0] or 0)
            except ValueError:
                raise ValueError(f"{name} must be an integer") from None

        length = int(self.headers.get("Content-Length", 0))
        from ..replication.geo import MAX_BODY_BYTES
        if length > MAX_BODY_BYTES:
            # the body is buffered whole before apply — an unbounded
            # Content-Length must not be an OOM lever.  The body goes
            # unread, so the connection cannot be reused afterwards.
            self.close_connection = True
            return self._send(413, json.dumps({
                "error": f"geo body {length} exceeds {MAX_BODY_BYTES}",
            }).encode(), extra={"Connection": "close"})
        body = self.rfile.read(length)
        try:
            out = fs.geo_applier.apply(
                origin=qi("origin"), source=qi("src"), seq=qi("seq"),
                hlc=qi("hlc"), op=q.get("op", [""])[0],
                path=q.get("path", [""])[0], data=body,
                mime=q.get("mime", [""])[0],
                log=q.get("log", [""])[0],
            )
        except QuotaExceededError as e:
            return self._send(403, json.dumps({"error": str(e)}).encode(),
                              extra={"X-Seaweed-Reject": "quota"})
        except ValueError as e:
            from ..replication.geo import GeoSkewError
            if isinstance(e, GeoSkewError):
                # remote-STATE rejection (sender's clock broken, clears
                # over operator time): marked so the sender HOLDS the
                # link instead of skipping events past its checkpoint
                return self._send(
                    400, json.dumps({"error": str(e)}).encode(),
                    extra={"X-Seaweed-Reject": "skew"})
            return self._json(400, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 — sender retries on 500
            return self._json(500, {
                "error": str(e),
                "trace": trace.current_trace_id() or "",
            })
        return self._json(200, out)

    # -- write -------------------------------------------------------------

    def do_POST(self):
        if self.path.startswith("/.geo/"):
            with http_request(self, "filer", "geo"):
                return self._geo_post()
        with http_request(self, "filer", "post"):
            self._admitted(self._upload)

    def do_PUT(self):
        with http_request(self, "filer", "post"):
            self._admitted(self._upload)

    def _quota_reject(self, e: QuotaExceededError):
        return self._send(403, json.dumps({"error": str(e)}).encode(),
                          extra={"X-Seaweed-Reject": "quota"})

    def _upload(self):
        u = urllib.parse.urlparse(self.path)
        path = urllib.parse.unquote(u.path)
        q = urllib.parse.parse_qs(u.query)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        ctype = self.headers.get("Content-Type", "")
        name_hint = b""
        if ctype.startswith("multipart/form-data"):
            from ..volume.http_handlers import _parse_multipart

            body, name_hint, part_mime = _parse_multipart(body, ctype)
            if part_mime:
                ctype = part_mime.decode()
        if path.endswith("/"):
            # upload INTO a directory: use the part filename
            if not name_hint:
                return self._json(400, {"error": "no filename for directory upload"})
            path = path + name_hint.decode(errors="replace")
        collection = q.get("collection", [""])[0] or self.filer.bucket_collection(path)
        ttl = q.get("ttl", [""])[0]
        if q.get("op", [""])[0] == "append":
            try:
                entry = self.filer_server.append_file(
                    path, body, mime=ctype, collection=collection,
                    replication=q.get("replication", [""])[0], ttl=ttl,
                )
            except QuotaExceededError as e:
                return self._quota_reject(e)
            except Exception as e:
                return self._json(500, {
                    "error": str(e),
                    "trace": trace.current_trace_id() or "",
                })
            return self._json(201, {
                "name": entry.name,
                "size": filechunks.total_size(entry.chunks),
            })
        try:
            entry = self.filer_server.write_file(
                path, body,
                mime=ctype if ctype and not ctype.startswith("multipart") else "",
                collection=collection,
                replication=q.get("replication", [""])[0],
                ttl=ttl,
                signatures=_signatures(q),
            )
        except QuotaExceededError as e:
            return self._quota_reject(e)
        except Exception as e:
            return self._json(500, {
                "error": str(e),
                "trace": trace.current_trace_id() or "",
            })
        self._json(201, {
            "name": entry.name,
            "size": filechunks.total_size(entry.chunks) or len(entry.content),
        })

    # -- delete ------------------------------------------------------------

    def do_DELETE(self):
        with http_request(self, "filer", "delete"):
            self._admitted(self._do_delete)

    def _do_delete(self):
        u = urllib.parse.urlparse(self.path)
        path = urllib.parse.unquote(u.path)
        q = urllib.parse.parse_qs(u.query)
        recursive = q.get("recursive", ["false"])[0] == "true"
        directory, name = split_path(path)
        try:
            self.filer.delete_entry(
                directory, name, is_recursive=recursive,
                ignore_recursive_error=q.get("ignoreRecursiveError", ["false"])[0] == "true",
                signatures=_signatures(q),
            )
        except FileNotFoundError:
            return self._json(404, {"error": f"{path}: not found"})
        except IsADirectoryError as e:
            return self._json(400, {"error": str(e)})
        self._send(204)


def _signatures(q: dict) -> list[int]:
    """?signature=N (repeatable): mutation provenance markers so metadata
    subscribers can skip events they caused themselves (filer.sync loop
    prevention, command/filer_sync.go)."""
    out = []
    for v in q.get("signature", []):
        try:
            out.append(int(v))
        except ValueError:
            continue
    return out


def _entry_json(dir_path: str, e: filer_pb2.Entry) -> dict:
    return {
        "FullPath": join_path("/" + dir_path.strip("/") if dir_path != "/" else "/", e.name),
        "IsDirectory": e.is_directory,
        "FileSize": filechunks.total_size(e.chunks) or e.attributes.file_size or len(e.content),
        "Mtime": e.attributes.mtime,
        "Crtime": e.attributes.crtime,
        "Mime": e.attributes.mime,
        "Chunks": len(e.chunks),
    }



shield_handler(FilerHttpHandler, "_json")


def serve_http(filer_server, host: str, port: int):
    handler = type(
        "BoundFilerHttpHandler", (FilerHttpHandler,),
        {"filer_server": filer_server},
    )
    # opts into the event loop only under SEAWEEDFS_TPU_EVENTLOOP=all
    httpd = make_http_server((host, port), handler, surface="filer")
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
