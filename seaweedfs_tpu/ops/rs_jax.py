"""TPU-native Reed-Solomon codec: GF(2^8) matmul as JAX/XLA programs.

This replaces the reference's SIMD-assembly GF kernel (klauspost/reedsolomon,
the hot loop at weed/storage/erasure_coding/ec_encoder.go:179
`enc.Encode(buffers)`) with two TPU formulations:

1. ``xor`` (VPU): GF multiply distributes over the bit decomposition of the
   constant:  c*x = XOR_{k: bit k of c} (2^k * x).  We compute the eight
   doubling multiples 2^k*x once per input shard (a fused chain of shifts and
   conditional reductions by 0x1D) and XOR together the multiples selected by
   the generator matrix.  With the matrix baked in at trace time XLA constant-
   folds the selection into a static XOR network and fuses the whole encode
   into one elementwise kernel: 10 streams in, 4 streams out, no
   intermediates in HBM.

2. ``mxu`` (systolic array): over GF(2) the codec is linear in *bits*, so
   unpack bytes to bit-planes, multiply by the 8Rx8C 0/1 matrix of
   ``gf256.bit_matrix`` as an int8 matmul (int32 accumulation), take parity
   (&1), and repack.  256 MACs/byte keeps the MXU busy and the op
   HBM-bandwidth-bound.

Both are shape-polymorphic in the block length B and are reused by the
multi-volume sharded encoder in seaweedfs_tpu.parallel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256
from ..telemetry import trace

_REDUCE = 0x1D  # low byte of the field polynomial 0x11D


def _multiples(data: jax.Array) -> list[jax.Array]:
    """[data * 2^k for k in 0..7] — the doubling chain in GF(2^8).

    data: uint8 (..., B).  Each step: x*2 = (x << 1) ^ (0x1D if x & 0x80).
    """
    ms = [data]
    x = data
    for _ in range(7):
        hi = x >> 7  # 0 or 1
        x = ((x << 1) ^ (hi * jnp.uint8(_REDUCE))).astype(jnp.uint8)
        ms.append(x)
    return ms


def _xor_network(rows: tuple[tuple[int, ...], ...], data: jax.Array) -> jax.Array:
    """Apply a constant GF matrix to (S, B) data via the XOR network."""
    ms = _multiples(data)
    outs = []
    for row in rows:
        acc = None
        for j, c in enumerate(row):
            for k in range(8):
                if (c >> k) & 1:
                    term = ms[k][j]
                    acc = term if acc is None else acc ^ term
        outs.append(acc if acc is not None else jnp.zeros_like(data[0]))
    return jnp.stack(outs)


@functools.lru_cache(maxsize=None)
def make_apply_xor(rows: tuple[tuple[int, ...], ...]):
    """Jitted (S, B) uint8 -> (R, B) uint8 GF matmul with baked constants."""

    @jax.jit
    def apply(data: jax.Array) -> jax.Array:
        return _xor_network(rows, data)

    return apply


@functools.lru_cache(maxsize=None)
def make_apply_mxu(rows: tuple[tuple[int, ...], ...]):
    """Jitted GF matmul on the MXU via the bit-plane int8 matmul."""
    m = np.array(rows, dtype=np.uint8)
    a = gf256.bit_matrix(m).astype(np.int8)  # (8R, 8S)

    @jax.jit
    def apply(data: jax.Array) -> jax.Array:
        s, b = data.shape
        shifts = jnp.arange(8, dtype=jnp.uint8)
        bits = ((data[:, None, :] >> shifts[None, :, None]) & 1).astype(jnp.int8)
        bits = bits.reshape(s * 8, b)
        acc = jax.lax.dot_general(
            jnp.asarray(a),
            bits,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (8R, B)
        pbits = (acc & 1).astype(jnp.uint8).reshape(-1, 8, b)
        out = pbits[:, 0, :]
        for k in range(1, 8):
            out = out | (pbits[:, k, :] << k)
        return out

    return apply


def _rows_of(matrix: np.ndarray) -> tuple[tuple[int, ...], ...]:
    return tuple(tuple(int(c) for c in row) for row in np.asarray(matrix))


def _impl_fn(rows: tuple[tuple[int, ...], ...], impl: str):
    if impl == "xor":
        return make_apply_xor(rows)
    if impl == "mxu":
        return make_apply_mxu(rows)
    if impl == "pallas":
        from .rs_pallas import make_apply_pallas

        return make_apply_pallas(rows)
    raise ValueError(f"unknown jax codec impl {impl!r}")


def apply_matrix(
    matrix: np.ndarray, data: jax.Array, impl: str = "xor"
) -> jax.Array:
    """GF matmul: (R, S) constant matrix x (S, B) device data -> (R, B)."""
    return _impl_fn(_rows_of(matrix), impl)(data)


class ReedSolomonTPU:
    """RS(data, parity) codec running the GF matmul on the accelerator.

    API mirrors ops.rs_cpu.ReedSolomon (encode / reconstruct /
    reconstruct_data over lists of equal-length uint8 numpy arrays), plus
    device-resident entry points (encode_device) used by the streaming file
    encoder and the multi-volume mesh pipeline.
    """

    def __init__(
        self,
        data_shards: int = 10,
        parity_shards: int = 4,
        impl: str = "xor",
    ):
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.impl = impl
        self.matrix = gf256.rs_matrix(data_shards, self.total_shards)
        self._parity_rows = _rows_of(self.matrix[data_shards:])

    # -- device-resident --------------------------------------------------

    def encode_device(self, data: jax.Array) -> jax.Array:
        """(data_shards, B) uint8 on device -> (parity_shards, B) parity."""
        return _impl_fn(self._parity_rows, self.impl)(data)

    def encode_device_u32(self, d32: jax.Array) -> jax.Array | None:
        """(data_shards, B/4) uint32 -> (parity_shards, B/4) parity words.

        Zero-relayout entry for bulk pipelines: the host views its uint8
        buffers as little-endian uint32 (free) and the kernel works on packed
        words directly — no device-side bitcast.  Returns None when the
        active impl has no packed entry (caller falls back to uint8).
        """
        fn = _impl_fn(self._parity_rows, self.impl)
        as_u32 = getattr(fn, "as_u32", None)
        return None if as_u32 is None else as_u32(d32)

    def encode_device_u32_3d(self, d3: jax.Array) -> jax.Array | None:
        """(data_shards, R, 128) uint32 lane tiles -> (parity_shards, R, 128).

        The zero-reshape bulk entry (rs_pallas apply32_3d): the jitted
        program is exactly the kernel, so XLA cannot choose a transposed
        parameter layout that pads the shard dim 10->128 in HBM.
        """
        fn = _impl_fn(self._parity_rows, self.impl)
        as_3d = getattr(fn, "as_u32_3d", None)
        return None if as_3d is None else as_3d(d3)

    def apply_rows_device(self, rows: np.ndarray, inputs: jax.Array) -> jax.Array:
        """Arbitrary GF matrix application (used for decode/rebuild)."""
        return apply_matrix(rows, inputs, self.impl)

    def parity_of(self, data: np.ndarray) -> np.ndarray:
        """(data_shards, B) -> (parity_shards, B), the bulk-pipeline entry.

        The three hops are spanned separately so a slow rebuild is
        attributable to transfer vs compute (behind a thin tunnel the
        device put dominates; on a pod host the kernel does)."""
        assert data.shape[0] == self.data_shards
        with trace.child_span("ec.device_put", impl=self.impl,
                              bytes=int(data.nbytes)):
            dev = jnp.asarray(data)
        with trace.child_span("ec.device_compute", impl=self.impl):
            # jit dispatch is async: block here so compute time lands in
            # THIS span, not misattributed to the device_get transfer
            parity = jax.block_until_ready(self.encode_device(dev))
        with trace.child_span("ec.device_get", impl=self.impl):
            return np.asarray(parity)

    # -- numpy convenience (same shapes as rs_cpu) ------------------------

    def encode(self, shards: list[np.ndarray]) -> None:
        data = np.stack(shards[: self.data_shards])
        parity = self.parity_of(data)
        for i in range(self.parity_shards):
            shards[self.data_shards + i][:] = parity[i]

    def _reconstruct(self, shards, data_only: bool):
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) == self.total_shards:
            return list(shards)
        if len(present) < self.data_shards:
            raise ValueError("too few shards to reconstruct")
        sub = present[: self.data_shards]
        stacked = np.stack([shards[i] for i in sub])
        with trace.child_span("ec.device_put", impl=self.impl,
                              bytes=int(stacked.nbytes)):
            inputs = jnp.asarray(stacked)
        out = list(shards)
        missing_data = [i for i in range(self.data_shards) if shards[i] is None]
        if missing_data:
            rows = gf256.decode_plan_for(
                self.matrix, self.data_shards, present, tuple(missing_data))
            with trace.child_span("ec.device_compute", impl=self.impl):
                dev = jax.block_until_ready(
                    self.apply_rows_device(rows, inputs))
            with trace.child_span("ec.device_get", impl=self.impl):
                rec = np.asarray(dev)
            for i, r in zip(missing_data, rec):
                out[i] = r
        if not data_only:
            missing_parity = [
                i for i in range(self.data_shards, self.total_shards)
                if shards[i] is None
            ]
            if missing_parity:
                data = jnp.asarray(
                    np.stack([np.asarray(out[i]) for i in range(self.data_shards)])
                )
                rows = self.matrix[np.asarray(missing_parity)]
                par = np.asarray(self.apply_rows_device(rows, data))
                for i, p in zip(missing_parity, par):
                    out[i] = p
        return out

    def reconstruct(self, shards):
        return self._reconstruct(shards, data_only=False)

    def reconstruct_data(self, shards):
        return self._reconstruct(shards, data_only=True)

    def verify(self, shards: list[np.ndarray]) -> bool:
        data = np.stack(shards[: self.data_shards])
        parity = np.asarray(self.encode_device(jnp.asarray(data)))
        return all(
            np.array_equal(parity[i], shards[self.data_shards + i])
            for i in range(self.parity_shards)
        )
