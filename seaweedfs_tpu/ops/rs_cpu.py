"""Host-side Reed-Solomon codec (numpy, with optional C++ SIMD fast path).

This is the CPU member of the codec family behind the `-ec.codec` switch
(reference behavior: weed/storage/erasure_coding/ec_encoder.go uses
klauspost/reedsolomon for Encode/Reconstruct).  Semantics mirror that
encoder's API surface:

  * encode(shards):           fills parity shards from data shards
  * reconstruct(shards):      fills ALL missing shards (None entries)
  * reconstruct_data(shards): fills only missing DATA shards

Shard arrays are numpy uint8 1-D of equal length.  The per-needle degraded
read path uses this codec (small intervals must not pay a TPU dispatch —
SURVEY.md §7 hard part (c)); bulk encode/rebuild goes to rs_jax.
"""

from __future__ import annotations

import numpy as np

from . import gf256


class ReedSolomon:
    """RS(data, parity) systematic codec over GF(2^8)."""

    def __init__(self, data_shards: int = 10, parity_shards: int = 4):
        if data_shards <= 0 or parity_shards < 0:
            raise ValueError("bad shard counts")
        if data_shards + parity_shards > 256:
            raise ValueError("too many shards for GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        self.matrix = gf256.rs_matrix(data_shards, self.total_shards)
        self.parity_matrix = self.matrix[data_shards:]
        self._mul = gf256.mul_table()

    # -- core matmul ------------------------------------------------------

    def _apply(self, rows: np.ndarray, inputs: list[np.ndarray]) -> list[np.ndarray]:
        """outputs[i] = XOR_j mul(rows[i,j], inputs[j]) via table lookups.

        Uses the C++ SSSE3 nibble-table codec when available — decode/
        rebuild matrices go through the same kernel as encode parity, so
        reconstruction is not left on the slow numpy path."""
        from ..native import lib as native

        if len(inputs) > 1 and any(len(x) != len(inputs[0])
                                   for x in inputs[1:]):
            # the C kernel indexes every input by len(inputs[0]) — a
            # shorter shard would be read out of bounds
            raise ValueError("input shards must be the same length")
        if native.available() and rows.size and len(inputs):
            return native.gf_apply_arrays(rows, list(inputs))
        n = len(inputs)
        outs = []
        for i in range(rows.shape[0]):
            acc = None
            for j in range(n):
                c = int(rows[i, j])
                if c == 0:
                    continue
                term = inputs[j] if c == 1 else self._mul[c][inputs[j]]
                acc = term.copy() if acc is None else np.bitwise_xor(acc, term, out=acc)
            if acc is None:
                acc = np.zeros_like(inputs[0])
            outs.append(acc)
        return outs

    # -- public API -------------------------------------------------------

    def apply_rows(self, rows: np.ndarray,
                   inputs: list[np.ndarray]) -> list[np.ndarray]:
        """Arbitrary GF matrix application over equal-length byte rows —
        the decode-plan entry used by the pipelined rebuild (same native
        SIMD kernel as encode parity)."""
        return self._apply(rows, inputs)

    def parity_into(self, inputs: list[np.ndarray],
                    outs: list[np.ndarray]) -> None:
        """Parity from arbitrary equal-length contiguous 1-D row buffers
        into preallocated outputs — the zero-copy entry for the mmap'd
        encode pipeline (rows may be views straight into the page cache)."""
        from ..native import lib as native

        # the native kernel writes len(inputs[0]) bytes through each raw
        # out pointer with no checks of its own — validate here so a bad
        # caller gets a ValueError on every host, not a heap scribble on
        # SIMD hosts and a broadcast error on the numpy fallback
        if len(inputs) != self.data_shards:
            raise ValueError(
                f"expected {self.data_shards} input rows, got {len(inputs)}")
        if len(outs) != self.parity_shards:
            raise ValueError(
                f"expected {self.parity_shards} output rows, got {len(outs)}")
        n = len(inputs[0])
        if any(len(o) != n for o in outs):
            raise ValueError("output rows must match input length")
        if native.available():
            native.gf_apply_arrays(self.parity_matrix, inputs, out=outs)
            return
        for o, r in zip(outs, self._apply(self.parity_matrix, inputs)):
            o[:] = r

    def parity_of(self, data: np.ndarray) -> np.ndarray:
        """(data_shards, B) -> (parity_shards, B), the bulk-pipeline entry;
        _apply picks the native GFNI/SSSE3 kernel when available."""
        assert data.shape[0] == self.data_shards
        from ..native import lib as native

        if native.available() and data.flags["C_CONTIGUOUS"]:
            # rows of a preallocated output avoid the np.stack copy
            out = np.empty((self.parity_shards, data.shape[1]), np.uint8)
            native.gf_apply_arrays(self.parity_matrix, list(data),
                                   out=list(out))
            return out
        return np.stack(self._apply(self.parity_matrix, list(data)))

    def encode(self, shards: list[np.ndarray]) -> None:
        """Fill shards[data:] (parity) in place from shards[:data]."""
        self._check(shards, need_all_data=True)
        parity = self._apply(self.parity_matrix, shards[: self.data_shards])
        for i, p in enumerate(parity):
            shards[self.data_shards + i][:] = p

    def verify(self, shards: list[np.ndarray]) -> bool:
        parity = self._apply(self.parity_matrix, shards[: self.data_shards])
        return all(
            np.array_equal(p, shards[self.data_shards + i])
            for i, p in enumerate(parity)
        )

    def reconstruct(self, shards: list[np.ndarray | None]) -> list[np.ndarray]:
        return self._reconstruct(shards, data_only=False)

    def reconstruct_one(
        self, shards: list[np.ndarray | None], shard_id: int
    ) -> np.ndarray:
        """Decode ONLY shard_id from >= data_shards present shards.

        The per-needle degraded read needs exactly one missing interval;
        computing all 4 lost rows (reconstruct) would quadruple the GF
        work on the latency path (store_ec.go's ReconstructData analogue,
        narrowed to the single wanted row)."""
        if shards[shard_id] is not None:
            return np.asarray(shards[shard_id], dtype=np.uint8)
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.data_shards:
            raise ValueError("too few shards to reconstruct")
        sub = present[: self.data_shards]
        sub_shards = [np.asarray(shards[i], dtype=np.uint8) for i in sub]
        # one cached plan row per (survivor set, shard): the inversion AND
        # the parity-row composition both come out of the shared cache
        row = gf256.decode_plan_for(
            self.matrix, self.data_shards, present, (shard_id,))
        return self._apply(row, sub_shards)[0]

    def reconstruct_data(self, shards: list[np.ndarray | None]) -> list[np.ndarray]:
        return self._reconstruct(shards, data_only=True)

    def _reconstruct(
        self, shards: list[np.ndarray | None], data_only: bool
    ) -> list[np.ndarray]:
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} shard slots")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) == self.total_shards:
            return list(shards)  # type: ignore[arg-type]
        if len(present) < self.data_shards:
            raise ValueError("too few shards to reconstruct")
        size = len(shards[present[0]])  # type: ignore[index]

        sub = present[: self.data_shards]
        sub_shards = [np.asarray(shards[i], dtype=np.uint8) for i in sub]
        missing_data = [
            i for i in range(self.data_shards) if shards[i] is None
        ]
        out = list(shards)

        if missing_data:
            rows = gf256.decode_plan_for(
                self.matrix, self.data_shards, present, tuple(missing_data))
            recovered = self._apply(rows, sub_shards)
            for i, r in zip(missing_data, recovered):
                out[i] = r

        if not data_only:
            missing_parity = [
                i
                for i in range(self.data_shards, self.total_shards)
                if shards[i] is None
            ]
            if missing_parity:
                data = [np.asarray(out[i], dtype=np.uint8) for i in range(self.data_shards)]
                rows = self.matrix[np.asarray(missing_parity)]
                parity = self._apply(rows, data)
                for i, p in zip(missing_parity, parity):
                    out[i] = p
        for i, s in enumerate(out):
            if s is not None and len(s) != size:
                raise ValueError("shard size mismatch")
        return out  # type: ignore[return-value]

    def _check(self, shards: list[np.ndarray], need_all_data: bool) -> None:
        if len(shards) != self.total_shards:
            raise ValueError(f"expected {self.total_shards} shards")
        size = len(shards[0])
        for s in shards:
            if len(s) != size:
                raise ValueError("shards must be equal length")
