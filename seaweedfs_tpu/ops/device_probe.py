"""Fast accelerator-reachability probe with a hard deadline.

One question, answered in seconds and cached for the process lifetime:
*can this host's jax produce working devices right now?*  Every consumer
that used to discover an unreachable TPU by timing out on its own —
``get_codec`` device-codec selection, ``-ec.codec=auto`` resolution, the
codec service's mode pick, every TPU-touching bench stage — asks here
instead, so a wedged transport degrades the caller to the host SIMD
codec in ``SEAWEEDFS_TPU_PROBE_TIMEOUT_S`` (default 10s), not after the
300s stage timeouts that poisoned BENCH_r04/r05.

The check runs in a KILLABLE subprocess: a wedged device tunnel hangs
every in-process jax call including backend init, and threads cannot be
killed.  The child does a real host->device->host round trip, not just a
device listing — a transport that enumerates but cannot move bytes must
count as unreachable.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass

DEFAULT_TIMEOUT_S = 10.0

# the child prints ONE json line after the round trip; anything else
# (hang, crash, refused backend init) is a failed probe
_CHILD_CODE = r"""
import json, os, sys
import jax
_p = os.environ.get('JAX_PLATFORMS')
if _p:
    # the ambient sitecustomize may preload jax on the accelerator
    # platform before JAX_PLATFORMS is read; re-assert the caller's
    # choice via config, which wins if set before backend init
    jax.config.update('jax_platforms', _p)
if (_p or '').split(',')[0] == 'cpu':
    # a cpu pin must not hang on a wedged accelerator auto-init hook
    try:
        from seaweedfs_tpu.util.jaxenv import force_cpu_backend
        force_cpu_backend()
    except Exception:
        pass
import numpy as np
import jax.numpy as jnp
d = jax.devices()
np.asarray(jnp.ones((8, 128)) + 1)  # round trip, not just init
print(json.dumps({'devices': len(d),
                  'platform': d[0].platform if d else ''}))
"""


@dataclass(frozen=True)
class ProbeResult:
    ok: bool
    devices: int = 0
    platform: str = ""
    seconds: float = 0.0
    error: str = ""

    @property
    def accelerator(self) -> bool:
        """True when a non-CPU backend answered the round trip — the
        gate for dispatching bulk GF work to a device."""
        return self.ok and self.platform not in ("", "cpu")

    def to_json(self) -> dict:
        out: dict = {"devices": self.devices, "platform": self.platform,
                     "probe_seconds": round(self.seconds, 2)}
        if not self.ok:
            out["error"] = self.error or "probe failed"
        return out


_LOCK = threading.Lock()
_CACHED: ProbeResult | None = None


def probe_timeout_s() -> float:
    try:
        return float(os.environ.get(
            "SEAWEEDFS_TPU_PROBE_TIMEOUT_S", str(DEFAULT_TIMEOUT_S)))
    except ValueError:
        return DEFAULT_TIMEOUT_S


def _run_probe(timeout_s: float) -> ProbeResult:
    import importlib.util
    import subprocess
    import sys

    t0 = time.perf_counter()
    if importlib.util.find_spec("jax") is None:
        return ProbeResult(ok=False, error="jax not installed",
                           seconds=time.perf_counter() - t0)
    env = dict(os.environ)
    # the child must resolve seaweedfs_tpu the same way the parent did,
    # even when the package is only importable via the parent's
    # script-dir sys.path entry
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in sys.path if p] +
        [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_CODE], capture_output=True,
            text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return ProbeResult(
            ok=False, seconds=time.perf_counter() - t0,
            error=f"device probe timed out after {timeout_s:.0f}s")
    except Exception as exc:  # fork failure, odd embedding — never raise
        return ProbeResult(
            ok=False, seconds=time.perf_counter() - t0,
            error=f"{type(exc).__name__}: {exc}"[:300])
    dt = time.perf_counter() - t0
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()
        return ProbeResult(
            ok=False, seconds=dt,
            error=(tail[-1] if tail else f"probe rc={proc.returncode}")[:300])
    parsed = None
    for line in reversed((proc.stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            break
        except (json.JSONDecodeError, ValueError):
            continue
    if not isinstance(parsed, dict) or "devices" not in parsed:
        return ProbeResult(ok=False, seconds=dt,
                           error="probe emitted no device report")
    return ProbeResult(
        ok=int(parsed["devices"]) >= 1,
        devices=int(parsed["devices"]),
        platform=str(parsed.get("platform", "")),
        seconds=dt,
        error="" if int(parsed["devices"]) >= 1 else "no devices",
    )


def probe(timeout_s: float | None = None, refresh: bool = False) -> ProbeResult:
    """Cached reachability verdict; the subprocess runs at most once per
    process (per explicit ``refresh``).  ``timeout_s`` overrides the env
    knob for this call only — it has no effect on a cache hit."""
    global _CACHED
    if not refresh:
        cached = _CACHED
        if cached is not None:
            return cached
    with _LOCK:
        if not refresh and _CACHED is not None:
            return _CACHED
        result = _run_probe(
            probe_timeout_s() if timeout_s is None else timeout_s)
        _CACHED = result
        return result


def reset_cache() -> None:
    """Forget the cached verdict (tests; long-lived admin shells)."""
    global _CACHED
    with _LOCK:
        _CACHED = None
