"""Pallas TPU kernel for the GF(2^8) RS matmul — the hot encode/decode op.

Why a hand kernel: the jnp XOR-network formulation (rs_jax.py) is correct
but XLA materialises the eight doubling-chain multiples as full HBM temps
(each consumed by several parity outputs, so fusion CSEs them into kLoop
fusion outputs) — ~8x extra HBM traffic and OOM at large blocks.  Here the
whole multiply-accumulate network runs per VMEM tile: grid over column
blocks, each step DMAs a (S, R, 128) tile in, computes the doubling chain
and the constant-selected XOR accumulation on the VPU, and writes the
(R_out, R, 128) parity tile — HBM traffic is exactly input+output.

SWAR trick: Mosaic has no u8 vector shifts, so bytes are packed four-to-a-
lane as uint32 and the doubling step works on all four at once:

    x*2 (per byte) = ((x << 1) & 0xFEFEFEFE) ^ (((x >> 7) & 0x01010101) * 0x1D)

The high-bit extraction keeps bytes independent (0x1D < 0x100, no carries),
so one u32 op stream processes 4 GF bytes per lane — 512 bytes per VPU op
at full lane width.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import gf256

LANES = 128
BYTES_PER_LANE = 4  # uint32 SWAR packing
_REDUCE = 0x1D1D1D1D
_HI_MASK = 0x80808080
_LO7_MASK = 0x7F7F7F7F
_ONE_MASK = 0x01010101

# sublane rows per grid step: each input tile is (S, SUBLANES, 128) u32
# = SUBLANES*512 bytes per shard per step
SUBLANES = 256  # 128KB/shard/step; 14 shards ~ 1.8MB VMEM live per stage


def _kernel_body(rows: tuple[tuple[int, ...], ...], data_ref, out_ref):
    """data_ref: (S, R, 128) u32; out_ref: (R_out, R, 128) u32."""
    n_out = len(rows)
    s = len(rows[0])
    max_bit = [0] * s
    for row in rows:
        for j, c in enumerate(row):
            for k in range(8):
                if (c >> k) & 1:
                    max_bit[j] = max(max_bit[j], k)
    accs: list = [None] * n_out
    for j in range(s):
        x = data_ref[j]
        for k in range(max_bit[j] + 1):
            if k > 0:
                hi = (x >> 7) & jnp.uint32(_ONE_MASK)
                x = ((x << 1) & jnp.uint32(0xFEFEFEFE)) ^ (
                    hi * jnp.uint32(0x1D)
                )
            for i in range(n_out):
                if (rows[i][j] >> k) & 1:
                    accs[i] = x if accs[i] is None else accs[i] ^ x
    for i in range(n_out):
        out_ref[i] = (
            accs[i] if accs[i] is not None else jnp.zeros_like(data_ref[0])
        )


def _auto_interpret(interpret: bool | None) -> bool:
    """interpret=None -> interpret off on real TPU, on elsewhere (CPU tests)."""
    if interpret is not None:
        return interpret
    return jax.default_backend() not in ("tpu", "axon")


@functools.lru_cache(maxsize=None)
def make_apply_pallas(
    rows: tuple[tuple[int, ...], ...], interpret: bool | None = None
):
    """Jitted (S, B) uint8 -> (R_out, B) uint8 GF matmul via a Pallas kernel.

    interpret=None auto-selects: compiled on TPU backends, interpreter mode
    elsewhere (so the same code path runs in CPU tests).
    """
    interpret = _auto_interpret(interpret)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_out = len(rows)
    s = len(rows[0])
    kernel = functools.partial(_kernel_body, rows)
    word_bytes = LANES * BYTES_PER_LANE  # 512 bytes per (row of) lane tile

    def _call_tiles(d3: jax.Array, rows_total: int, tile_rows: int) -> jax.Array:
        """(s, rows_total, LANES) u32, rows_total % tile_rows == 0 ->
        (n_out, rows_total, LANES); the one place the pallas_call is built."""
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((n_out, rows_total, LANES), jnp.uint32),
            grid=(rows_total // tile_rows,),
            in_specs=[
                pl.BlockSpec(
                    (s, tile_rows, LANES),
                    lambda g: (0, g, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (n_out, tile_rows, LANES),
                lambda g: (0, g, 0),
                memory_space=pltpu.VMEM,
            ),
            interpret=interpret,
        )(d3)

    def _run(d32: jax.Array) -> jax.Array:
        """(S, W) u32, W % LANES == 0 -> (n_out, W) u32."""
        w = d32.shape[1]
        rows_total = w // LANES
        tile_rows = min(SUBLANES, rows_total)
        grid = -(-rows_total // tile_rows)
        if rows_total % tile_rows:
            extra = grid * tile_rows - rows_total
            d32 = jnp.pad(d32, ((0, 0), (0, extra * LANES)))
            rows_total = grid * tile_rows
        d3 = d32.reshape(s, rows_total, LANES)
        out32 = _call_tiles(d3, rows_total, tile_rows)
        return out32.reshape(n_out, rows_total * LANES)[:, : w]

    @jax.jit
    def apply32(d32: jax.Array) -> jax.Array:
        """Zero-relayout path: bytes pre-packed as uint32 (4 GF bytes/word).

        Callers with bulk numpy data should `arr.view(np.uint32)` on the host
        (free) and use this entry — no device-side bitcast/copy at all.
        """
        assert d32.dtype == jnp.uint32 and d32.shape[0] == s
        w = d32.shape[1]
        padded = -(-w // LANES) * LANES
        if padded != w:
            d32 = jnp.pad(d32, ((0, 0), (0, padded - w)))
        out = _run(d32)
        return out[:, :w] if padded != w else out

    @jax.jit
    def _apply_u8(data: jax.Array) -> jax.Array:
        """(S, B) uint8 -> (n_out, B) uint8 (device-side repack for odd B)."""
        assert data.shape[0] == s, (data.shape, s)
        b = data.shape[1]
        padded = -(-b // word_bytes) * word_bytes
        if padded != b:
            data = jnp.pad(data, ((0, 0), (0, padded - b)))
        d4 = data.reshape(s, padded // word_bytes, LANES, BYTES_PER_LANE)
        d32 = jax.lax.bitcast_convert_type(d4, jnp.uint32).reshape(
            s, padded // BYTES_PER_LANE
        )
        out32 = _run(d32)
        out = jax.lax.bitcast_convert_type(
            out32.reshape(n_out, padded // word_bytes, LANES), jnp.uint8
        ).reshape(n_out, padded)
        return out[:, :b] if padded != b else out

    # the u8<->u32 bitcast prologue crashes this platform's compile helper
    # above ~16MB per shard (the raw pallas_call itself is fine at any
    # size), so the uint8 entry chunks wide inputs outside jit and
    # concatenates — each chunk is word-aligned so only the tail repads
    _U8_CHUNK = 16 << 20

    def apply(data: jax.Array) -> jax.Array:
        b = data.shape[1]
        if b <= _U8_CHUNK:
            return _apply_u8(data)
        outs = [
            _apply_u8(data[:, off:off + _U8_CHUNK])
            for off in range(0, b, _U8_CHUNK)
        ]
        return jnp.concatenate(outs, axis=1)

    @jax.jit
    def apply32_3d(d3: jax.Array) -> jax.Array:
        """(S, R, 128) u32 with R % min(SUBLANES, R) == 0 -> (n_out, R, 128).

        The fully pre-packed entry: the host views bytes as uint32 and
        reshapes to lane tiles itself, so the jitted program is EXACTLY the
        pallas_call — no reshape/pad ops whose layout assignment could
        materialise a transposed (shard-dim-minormost) copy in HBM.
        """
        assert d3.dtype == jnp.uint32 and d3.ndim == 3
        assert d3.shape[0] == s and d3.shape[2] == LANES
        rows_total = d3.shape[1]
        tile_rows = min(SUBLANES, rows_total)
        assert rows_total % tile_rows == 0, (rows_total, tile_rows)
        return _call_tiles(d3, rows_total, tile_rows)

    apply.as_u32 = apply32  # type: ignore[attr-defined]
    apply.as_u32_3d = apply32_3d  # type: ignore[attr-defined]
    return apply


def apply_matrix_pallas(
    matrix: np.ndarray, data: jax.Array, interpret: bool | None = None
) -> jax.Array:
    rows = tuple(tuple(int(c) for c in r) for r in np.asarray(matrix))
    return make_apply_pallas(rows, interpret)(data)


def parity_fn(data_shards: int = 10, parity_shards: int = 4,
              interpret: bool | None = None):
    """The flagship fused kernel: (10, B) stripe -> (4, B) parity."""
    m = gf256.rs_parity_matrix(data_shards, parity_shards)
    rows = tuple(tuple(int(c) for c in r) for r in m)
    return make_apply_pallas(rows, interpret)
