"""Codec registry — the `-ec.codec={cpu|tpu|tpu_xor|tpu_mxu}` switch.

The reference hardwires klauspost/reedsolomon; here every consumer (file
encoder, degraded reads, gRPC handlers, shell commands) goes through
``get_codec`` so the backend is a deployment choice.

Backends: ``cpu`` (numpy + C++ SIMD, no jax) · ``tpu`` (the Pallas SWAR
kernel — runs in interpreter mode off-TPU) · ``tpu_xor`` (fused XLA XOR
network) · ``tpu_mxu`` (bit-plane int8 matmul on the systolic array).

The TPU codec is imported lazily: the CPU-only per-needle path (storage
servers doing small degraded reads) must not pay a jax import, and must work
on hosts without jax at all.
"""

from __future__ import annotations

import time

from ..stats.metrics import EC_BYTES_HISTOGRAM, EC_OP_HISTOGRAM
from ..telemetry import trace
from .rs_cpu import ReedSolomon

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS


# ---------------------------------------------------------------------------
# EC-codec telemetry: every blocking codec call through get_codec records
# seaweedfs_ec_op_seconds{op,impl} + seaweedfs_ec_op_bytes{op,impl} and a
# span, so degraded-read and rebuild cost shows up in /metrics and
# /debug/traces attributed to the backend that did the GF math.
# ---------------------------------------------------------------------------


def _nbytes(x) -> int:
    if x is None:
        return 0
    n = getattr(x, "nbytes", None)
    if n is not None:
        return int(n)
    try:
        return len(x)
    except TypeError:
        return 0


def _arg_bytes(arg) -> int:
    if isinstance(arg, (list, tuple)):
        return sum(_nbytes(s) for s in arg)
    return _nbytes(arg)


class InstrumentedCodec:
    """Transparent telemetry proxy over a codec.

    Delegates everything (attributes, the device-resident async entries,
    hasattr-probed capabilities) and times only the BLOCKING operations —
    the async encode_device* futures are left alone because their wall
    time at dispatch is not the compute time; rs_jax spans cover those.
    """

    _TIMED = frozenset({
        "encode", "parity_of", "parity_into", "apply_rows",
        "reconstruct", "reconstruct_data", "reconstruct_one", "verify",
    })

    def __init__(self, inner, impl: str):
        self._inner = inner
        self._impl = impl

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name not in self._TIMED or not callable(attr):
            return attr
        impl = self._impl
        # histogram children and span name resolved ONCE per (op, impl):
        # the per-chunk encode loop must not pay registry-lock lookups
        # or import-machinery hits on every call
        op_hist = EC_OP_HISTOGRAM.labels(name, impl)
        bytes_hist = EC_BYTES_HISTOGRAM.labels(name, impl)
        span_name = f"ec.{name}"
        child_span = trace.child_span
        perf_counter = time.perf_counter

        def timed(*args, **kwargs):
            # max over the first two args: apply_rows leads with the tiny
            # plan matrix, every other op leads with the shard payload
            nbytes = max(
                (_arg_bytes(a) for a in args[:2]), default=0) if args else 0
            t0 = perf_counter()
            try:
                # metrics always; spans only inside an active trace — a
                # bulk encode calls this once per segment, and a root
                # span per segment would evict every request trace from
                # the ring
                with child_span(span_name, impl=impl, bytes=nbytes):
                    return attr(*args, **kwargs)
            finally:
                op_hist.observe(perf_counter() - t0)
                bytes_hist.observe(nbytes)

        timed.__name__ = name
        # cache on the instance: per-chunk hot paths (parity_into in the
        # encode loop) must not rebuild the closure every call
        self.__dict__[name] = timed
        return timed


def _instrument(codec, impl: str):
    return InstrumentedCodec(codec, impl)


def available_codecs() -> list[str]:
    """Canonical codec names usable with ``get_codec`` on this host."""
    import importlib.util

    names = ["auto", "cpu"]
    if importlib.util.find_spec("jax") is None:
        return names
    return names + ["tpu", "tpu_xor", "tpu_mxu"]


_AUTO_CHOICE: list[str] = []

# every name get_codec resolves to a jax-backed codec — the single
# source of truth shared with ops.codec_service's mode/routing logic
DEVICE_CODEC_NAMES = frozenset(
    {"tpu", "pallas", "tpu_pallas", "jax", "tpu_xor", "tpu_mxu", "mxu"})
_DEVICE_NAMES = DEVICE_CODEC_NAMES
_FALLBACK_WARNED: set[str] = set()


def effective_codec(name: str) -> tuple[str, str]:
    """-> (name that get_codec will actually build, fallback reason).

    Device codec names degrade to ``cpu`` when the fast reachability
    probe (ops.device_probe, hard deadline in seconds) says jax cannot
    produce devices — so a server started with ``-ec.codec=tpu`` on a
    host with a wedged transport comes up on the SIMD codec immediately
    instead of hanging every EC rpc for minutes.  The reason string is
    empty when no fallback happened."""
    if name not in _DEVICE_NAMES:
        return name, ""
    from . import device_probe

    pr = device_probe.probe()
    if pr.ok:
        return name, ""
    return "cpu", pr.error or "devices unreachable"


def _resolve_auto(probe_mb: int = 4, timeout_s: float = 75.0) -> str:
    """Pick the codec that will win the disk->shards pipeline on THIS host.

    The encode pipeline moves every input byte host->device and 0.4x back;
    on a pod host that link is PCIe/ICI (GB/s — device wins), behind a
    dev tunnel it can be single-digit MB/s (host SIMD wins).  So the probe
    times one real encode round trip (transfer in + kernel + transfer out)
    against the C++ SIMD codec on the same block, and the result is cached
    for the process lifetime.

    The device side runs in a KILLABLE subprocess with a hard timeout: a
    wedged transport hangs every device call including backend init, and a
    server starting with -ec.codec=auto must degrade to the host codec,
    not hang forever.
    """
    import importlib.util
    import os
    import subprocess
    import sys
    import time as _time

    if importlib.util.find_spec("jax") is None:
        return "cpu"
    # fast reachability gate first (seconds, cached): no devices, or only
    # a CPU backend, decides "cpu" without paying the timing subprocess —
    # and a wedged transport cannot burn the 75s budget below
    from . import device_probe

    pr = device_probe.probe()
    if not pr.ok or pr.platform == "cpu":
        return "cpu"
    import numpy as np

    block = np.zeros((DATA_SHARDS, probe_mb << 20), dtype=np.uint8)
    cpu = ReedSolomon(DATA_SHARDS, PARITY_SHARDS)
    cpu.parity_of(block)  # warm
    t0 = _time.perf_counter()
    cpu.parity_of(block)
    cpu_dt = _time.perf_counter() - t0

    code = (
        "import os, sys, time, numpy as np, jax\n"
        # the ambient sitecustomize may preload jax on the accelerator
        # platform before JAX_PLATFORMS is read; re-assert the caller's
        # choice via config, which wins if set before backend init
        "_p = os.environ.get('JAX_PLATFORMS')\n"
        "if _p:\n"
        "    jax.config.update('jax_platforms', _p)\n"
        # a CPU backend can never beat the in-process C++ SIMD codec —
        # skip the (interpret-mode, slow) device timing outright
        "print('PLATFORM', jax.default_backend()); sys.stdout.flush()\n"
        "if jax.default_backend() == 'cpu':\n"
        "    sys.exit(0)\n"
        "import jax.numpy as jnp\n"
        "from seaweedfs_tpu.ops.rs_jax import ReedSolomonTPU\n"
        f"block = np.zeros(({DATA_SHARDS}, {probe_mb} << 20), dtype=np.uint8)\n"
        f"tpu = ReedSolomonTPU({DATA_SHARDS}, {PARITY_SHARDS}, impl='pallas')\n"
        "np.asarray(tpu.encode_device(jnp.asarray(block)))\n"
        "t0 = time.perf_counter()\n"
        "np.asarray(tpu.encode_device(jnp.asarray(block)))\n"
        "print('DT', time.perf_counter() - t0)\n"
    )
    try:
        env = dict(os.environ)
        # the child must resolve seaweedfs_tpu the same way the parent
        # did, even when the package is only importable via the parent's
        # script-dir sys.path entry
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, env=env,
        )
    except Exception:  # wedged transport, fork failure, odd embedding —
        return "cpu"   # auto always degrades, never raises
    if proc.returncode != 0:  # no device / backend init refused
        return "cpu"
    tpu_dt = None
    for line in proc.stdout.splitlines():
        if line.startswith("DT "):
            tpu_dt = float(line.split()[1])
    if tpu_dt is None:
        return "cpu"
    return "tpu" if tpu_dt < cpu_dt else "cpu"


def get_codec(name: str = "cpu", data_shards: int = DATA_SHARDS,
              parity_shards: int = PARITY_SHARDS):
    """Return a codec with encode/reconstruct/reconstruct_data/verify."""
    if name == "auto":
        if not _AUTO_CHOICE:
            _AUTO_CHOICE.append(_resolve_auto())
        name = _AUTO_CHOICE[0]
    if name in _DEVICE_NAMES:
        name, reason = effective_codec(name)
        if reason and reason not in _FALLBACK_WARNED:
            _FALLBACK_WARNED.add(reason)
            from ..util import glog

            glog.warning(
                "ec codec: devices unreachable (%s); using cpu_simd", reason)
    if name in ("cpu", "go", "numpy"):
        return _instrument(ReedSolomon(data_shards, parity_shards), "cpu")
    if name in ("tpu", "pallas", "tpu_pallas"):
        from .rs_jax import ReedSolomonTPU

        return _instrument(
            ReedSolomonTPU(data_shards, parity_shards, impl="pallas"),
            "pallas")
    if name in ("jax", "tpu_xor"):
        from .rs_jax import ReedSolomonTPU

        return _instrument(
            ReedSolomonTPU(data_shards, parity_shards, impl="xor"), "xor")
    if name in ("tpu_mxu", "mxu"):
        from .rs_jax import ReedSolomonTPU

        return _instrument(
            ReedSolomonTPU(data_shards, parity_shards, impl="mxu"), "mxu")
    raise ValueError(f"unknown ec codec {name!r}")
