"""Codec registry — the `-ec.codec={cpu|tpu|tpu_xor|tpu_mxu}` switch.

The reference hardwires klauspost/reedsolomon; here every consumer (file
encoder, degraded reads, gRPC handlers, shell commands) goes through
``get_codec`` so the backend is a deployment choice.

Backends: ``cpu`` (numpy + C++ SIMD, no jax) · ``tpu`` (the Pallas SWAR
kernel — runs in interpreter mode off-TPU) · ``tpu_xor`` (fused XLA XOR
network) · ``tpu_mxu`` (bit-plane int8 matmul on the systolic array).

The TPU codec is imported lazily: the CPU-only per-needle path (storage
servers doing small degraded reads) must not pay a jax import, and must work
on hosts without jax at all.
"""

from __future__ import annotations

from .rs_cpu import ReedSolomon

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS


def available_codecs() -> list[str]:
    """Canonical codec names usable with ``get_codec`` on this host."""
    import importlib.util

    names = ["auto", "cpu"]
    if importlib.util.find_spec("jax") is None:
        return names
    return names + ["tpu", "tpu_xor", "tpu_mxu"]


_AUTO_CHOICE: list[str] = []


def _resolve_auto(probe_mb: int = 4, timeout_s: float = 75.0) -> str:
    """Pick the codec that will win the disk->shards pipeline on THIS host.

    The encode pipeline moves every input byte host->device and 0.4x back;
    on a pod host that link is PCIe/ICI (GB/s — device wins), behind a
    dev tunnel it can be single-digit MB/s (host SIMD wins).  So the probe
    times one real encode round trip (transfer in + kernel + transfer out)
    against the C++ SIMD codec on the same block, and the result is cached
    for the process lifetime.

    The device side runs in a KILLABLE subprocess with a hard timeout: a
    wedged transport hangs every device call including backend init, and a
    server starting with -ec.codec=auto must degrade to the host codec,
    not hang forever.
    """
    import importlib.util
    import os
    import subprocess
    import sys
    import time as _time

    if importlib.util.find_spec("jax") is None:
        return "cpu"
    import numpy as np

    block = np.zeros((DATA_SHARDS, probe_mb << 20), dtype=np.uint8)
    cpu = ReedSolomon(DATA_SHARDS, PARITY_SHARDS)
    cpu.parity_of(block)  # warm
    t0 = _time.perf_counter()
    cpu.parity_of(block)
    cpu_dt = _time.perf_counter() - t0

    code = (
        "import os, sys, time, numpy as np, jax\n"
        # the ambient sitecustomize may preload jax on the accelerator
        # platform before JAX_PLATFORMS is read; re-assert the caller's
        # choice via config, which wins if set before backend init
        "_p = os.environ.get('JAX_PLATFORMS')\n"
        "if _p:\n"
        "    jax.config.update('jax_platforms', _p)\n"
        # a CPU backend can never beat the in-process C++ SIMD codec —
        # skip the (interpret-mode, slow) device timing outright
        "print('PLATFORM', jax.default_backend()); sys.stdout.flush()\n"
        "if jax.default_backend() == 'cpu':\n"
        "    sys.exit(0)\n"
        "import jax.numpy as jnp\n"
        "from seaweedfs_tpu.ops.rs_jax import ReedSolomonTPU\n"
        f"block = np.zeros(({DATA_SHARDS}, {probe_mb} << 20), dtype=np.uint8)\n"
        f"tpu = ReedSolomonTPU({DATA_SHARDS}, {PARITY_SHARDS}, impl='pallas')\n"
        "np.asarray(tpu.encode_device(jnp.asarray(block)))\n"
        "t0 = time.perf_counter()\n"
        "np.asarray(tpu.encode_device(jnp.asarray(block)))\n"
        "print('DT', time.perf_counter() - t0)\n"
    )
    try:
        env = dict(os.environ)
        # the child must resolve seaweedfs_tpu the same way the parent
        # did, even when the package is only importable via the parent's
        # script-dir sys.path entry
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s, env=env,
        )
    except Exception:  # wedged transport, fork failure, odd embedding —
        return "cpu"   # auto always degrades, never raises
    if proc.returncode != 0:  # no device / backend init refused
        return "cpu"
    tpu_dt = None
    for line in proc.stdout.splitlines():
        if line.startswith("DT "):
            tpu_dt = float(line.split()[1])
    if tpu_dt is None:
        return "cpu"
    return "tpu" if tpu_dt < cpu_dt else "cpu"


def get_codec(name: str = "cpu", data_shards: int = DATA_SHARDS,
              parity_shards: int = PARITY_SHARDS):
    """Return a codec with encode/reconstruct/reconstruct_data/verify."""
    if name == "auto":
        if not _AUTO_CHOICE:
            _AUTO_CHOICE.append(_resolve_auto())
        name = _AUTO_CHOICE[0]
    if name in ("cpu", "go", "numpy"):
        return ReedSolomon(data_shards, parity_shards)
    if name in ("tpu", "pallas", "tpu_pallas"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="pallas")
    if name in ("jax", "tpu_xor"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="xor")
    if name in ("tpu_mxu", "mxu"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="mxu")
    raise ValueError(f"unknown ec codec {name!r}")
