"""Codec registry — the `-ec.codec={cpu|tpu|tpu_xor|tpu_mxu}` switch.

The reference hardwires klauspost/reedsolomon; here every consumer (file
encoder, degraded reads, gRPC handlers, shell commands) goes through
``get_codec`` so the backend is a deployment choice.

Backends: ``cpu`` (numpy + C++ SIMD, no jax) · ``tpu`` (the Pallas SWAR
kernel — runs in interpreter mode off-TPU) · ``tpu_xor`` (fused XLA XOR
network) · ``tpu_mxu`` (bit-plane int8 matmul on the systolic array).

The TPU codec is imported lazily: the CPU-only per-needle path (storage
servers doing small degraded reads) must not pay a jax import, and must work
on hosts without jax at all.
"""

from __future__ import annotations

from .rs_cpu import ReedSolomon

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS


def available_codecs() -> list[str]:
    """Canonical codec names usable with ``get_codec`` on this host."""
    import importlib.util

    names = ["cpu"]
    if importlib.util.find_spec("jax") is None:
        return names
    return names + ["tpu", "tpu_xor", "tpu_mxu"]


def get_codec(name: str = "cpu", data_shards: int = DATA_SHARDS,
              parity_shards: int = PARITY_SHARDS):
    """Return a codec with encode/reconstruct/reconstruct_data/verify."""
    if name in ("cpu", "go", "numpy"):
        return ReedSolomon(data_shards, parity_shards)
    if name in ("tpu", "pallas", "tpu_pallas"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="pallas")
    if name in ("jax", "tpu_xor"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="xor")
    if name in ("tpu_mxu", "mxu"):
        from .rs_jax import ReedSolomonTPU

        return ReedSolomonTPU(data_shards, parity_shards, impl="mxu")
    raise ValueError(f"unknown ec codec {name!r}")
