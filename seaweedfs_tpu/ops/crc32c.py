"""CRC32-C (Castagnoli) with the reference's masked finalisation.

The reference computes needle checksums with SIMD CRC32C
(weed/storage/needle/crc.go, klauspost/crc32) and stores a *masked* value:
``Value() = rotr(crc, 15) + 0xa282ead8`` (crc.go:25) — the LevelDB-style
masking.  We must write the identical 4 bytes into the needle body.

The hot path uses the C++ native library (hardware CRC32C via SSE4.2) when
available; this module is the always-present fallback: a numpy slicing-by-8
table implementation, plus the masking helpers.
"""

from __future__ import annotations

import functools

import numpy as np

_CASTAGNOLI = 0x82F63B78  # reflected polynomial


@functools.cache
def _tables() -> np.ndarray:
    """Slicing-by-8 tables, shape (8, 256) uint32."""
    t = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (_CASTAGNOLI if crc & 1 else 0)
        t[0, i] = crc
    for k in range(1, 8):
        for i in range(256):
            t[k, i] = (int(t[k - 1, i]) >> 8) ^ int(t[0, int(t[k - 1, i]) & 0xFF])
    return t


_native_update = None  # resolved once; False = no native lib


def update(crc: int, data: bytes | np.ndarray) -> int:
    """crc32c update (unmasked), matching crc32.Update over the Castagnoli table."""
    global _native_update
    if _native_update is None:
        try:
            from ..native import lib as _native

            _native_update = (_native.crc32c_update
                              if _native.available() else False)
        except Exception:
            _native_update = False
    if _native_update:
        return _native_update(crc, bytes(data))
    t = _tables()
    buf = np.frombuffer(bytes(data), dtype=np.uint8)
    crc = crc ^ 0xFFFFFFFF
    n = len(buf) - (len(buf) % 8)
    i = 0
    t0, t1, t2, t3, t4, t5, t6, t7 = (t[k] for k in range(8))
    while i < n:
        b = buf[i : i + 8]
        low = crc ^ (int(b[0]) | int(b[1]) << 8 | int(b[2]) << 16 | int(b[3]) << 24)
        crc = (
            int(t7[low & 0xFF])
            ^ int(t6[(low >> 8) & 0xFF])
            ^ int(t5[(low >> 16) & 0xFF])
            ^ int(t4[(low >> 24) & 0xFF])
            ^ int(t3[int(b[4])])
            ^ int(t2[int(b[5])])
            ^ int(t1[int(b[6])])
            ^ int(t0[int(b[7])])
        )
        i += 8
    t0_ = t[0]
    while i < len(buf):
        crc = (crc >> 8) ^ int(t0_[(crc ^ int(buf[i])) & 0xFF])
        i += 1
    return (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF


def checksum(data: bytes | np.ndarray) -> int:
    """Unmasked crc32c of a buffer (NewCRC(b) in the reference)."""
    return update(0, data)


def mask(crc: int) -> int:
    """The stored on-disk value: rotr(crc, 15) + 0xa282ead8 (mod 2^32)."""
    rot = ((crc >> 15) | (crc << 17)) & 0xFFFFFFFF
    return (rot + 0xA282EAD8) & 0xFFFFFFFF


def unmask(masked: int) -> int:
    """Inverse of mask(): recover the raw crc from the stored value —
    the zero-copy serving path reads only the on-disk (masked) checksum
    and must still answer the same Etag as the parse path."""
    rot = (masked - 0xA282EAD8) & 0xFFFFFFFF
    return ((rot << 15) | (rot >> 17)) & 0xFFFFFFFF


def value(data: bytes | np.ndarray) -> int:
    """Masked checksum as written into needle records."""
    return mask(checksum(data))
