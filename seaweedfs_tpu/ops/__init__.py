from . import crc32c, gf256  # noqa: F401
from .codec import DATA_SHARDS, PARITY_SHARDS, TOTAL_SHARDS, get_codec  # noqa: F401
from .rs_cpu import ReedSolomon  # noqa: F401

# NOTE: rs_jax (and thus jax) is intentionally NOT imported here — the
# CPU-only needle path must stay importable and cheap without jax.
