"""Pod-scale EC codec service: batched, double-buffered GF(2⁸) dispatch.

One bounded submission queue sits between every GF caller — the file
encoder, the rebuild pipeline, degraded reads, bench — and the compute
backend.  A scheduler thread drains it, coalesces jobs that share a
matrix (same generator rows or same decode plan) into one batch, and
dispatches the batch as a single compute call:

* **device mode**: batches are stacked into ``(V, S, W)`` blocks, padded
  to the mesh geometry, and run through the NamedSharding'd vmap GF
  matmul from ``parallel.mesh`` (the 16-volume batched encode shape
  verified in MULTICHIP_r05) — volumes shard over ``dp``, columns over
  ``sp``.  Up to two batches stay in flight: while batch *k* computes,
  batch *k+1* is assembled and dispatched, and *k*'s readback overlaps
  *k+1*'s compute — replacing the encoder's one-async-slice rule with
  true H2D/compute/D2H double buffering.

* **host mode**: the SAME scheduler runs on the C++ SIMD codec, so the
  batching and fairness properties hold on TPU-less hosts.  Small jobs
  coalesce column-wise into one reused slab and one native call; larger
  jobs run back to back through a prepared-pointer kernel entry
  (``native.gf_apply_fast``) that skips the ~15-20us of per-call Python
  the direct path pays.  On overhead-bound small-slice workloads this is
  where the aggregate win comes from: N producers' per-slice Python
  collapses into one worker's per-batch Python.

Callers that hold many independent jobs at once (the encoder has a whole
batch of stripe segments in hand) use the vectored ``submit_*_many``
entries: one lock acquisition and one wakeup for the group, which
matters more than any compute trick when jobs are tens of KB.

Fairness: batches always start from the queue HEAD (the oldest job), so
a saturating producer of one job class cannot starve another past one
batch's service time.  Byte identity with ``cpu_simd`` is structural:
host mode calls the same kernel, device mode runs the same XOR-network
formulation pinned byte-identical in tests/test_parallel.py.

Env knobs (all ``SEAWEEDFS_TPU_EC_SERVICE_*``): ``QUEUE`` (bound, 64),
``BATCH`` (max jobs/batch, 16), ``BATCH_MB`` (max input MB/batch, 64),
``COALESCE_KB`` (host slab threshold per job, 16), ``DEGRADED`` ("1"
routes degraded-read interval decodes through the service), and the
top-level ``SEAWEEDFS_TPU_EC_SERVICE`` ("0" disables every default
wiring).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

import numpy as np

from ..stats.metrics import (
    EC_SERVICE_BATCH_BYTES,
    EC_SERVICE_BATCH_JOBS,
    EC_SERVICE_FLUSH,
    EC_SERVICE_INFLIGHT,
    EC_SERVICE_JOB_SECONDS,
    EC_SERVICE_JOBS,
    EC_SERVICE_QUEUE_DEPTH,
    EC_SERVICE_STAGE,
)
from . import device_probe
from .codec import DEVICE_CODEC_NAMES as _DEVICE_CODECS
from .rs_cpu import ReedSolomon

DATA_SHARDS = 10
PARITY_SHARDS = 4

_STAGE_BUILD = EC_SERVICE_STAGE.labels("build")
_STAGE_COMPUTE = EC_SERVICE_STAGE.labels("compute")
_STAGE_READBACK = EC_SERVICE_STAGE.labels("readback")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class _Job:
    __slots__ = ("kind", "key", "rows", "data", "width", "out",
                 "event", "result", "error", "t_submit")

    def __init__(self, kind, key, rows, data, width, out):
        self.kind = kind
        self.key = key
        self.rows = rows
        # (S, W) uint8 ndarray, or a list of S equal-length 1-D rows
        # (e.g. zero-copy views into an mmap'd .dat)
        self.data = data
        self.width = width
        self.out = out
        self.event = threading.Event()
        self.result = None  # (R, W) array-like of rows once delivered
        self.error: "Exception | None" = None
        self.t_submit = time.perf_counter()


class CodecFuture:
    """Handle for a submitted job; ``result()`` blocks until delivery
    and returns an (R, W) array-like — iterate it for the output rows."""

    __slots__ = ("_job",)

    def __init__(self, job: _Job):
        self._job = job

    def done(self) -> bool:
        return self._job.event.is_set()

    def result(self, timeout: "float | None" = None):
        if not self._job.event.wait(timeout):
            raise TimeoutError("codec service job not done")
        if self._job.error is not None:
            raise self._job.error
        return self._job.result


class CodecService:
    """Batched GF(2⁸) dispatch behind a bounded queue.

    ``mode``: ``host`` (SIMD), ``device`` (mesh-sharded jax), or ``auto``
    (device iff ``codec_name`` names a device codec AND the fast probe
    reports a reachable accelerator — an unreachable device degrades to
    host in probe-timeout seconds, never minutes).
    """

    def __init__(self, mode: str = "auto", codec_name: str = "cpu",
                 data_shards: int = DATA_SHARDS,
                 parity_shards: int = PARITY_SHARDS,
                 max_batch: "int | None" = None,
                 max_queue: "int | None" = None,
                 max_batch_mb: "int | None" = None,
                 coalesce_kb: "int | None" = None,
                 mesh=None):
        if mode not in ("auto", "host", "device"):
            raise ValueError(f"unknown codec service mode {mode!r}")
        self.fallback_reason = ""
        if mode == "auto":
            if codec_name in _DEVICE_CODECS:
                pr = device_probe.probe()
                if pr.accelerator:
                    mode = "device"
                else:
                    mode = "host"
                    self.fallback_reason = (
                        pr.error or f"no accelerator ({pr.platform or 'none'})")
            else:
                mode = "host"
        self.mode = mode
        self.codec_name = codec_name
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self._rs = ReedSolomon(data_shards, parity_shards)
        self.matrix = self._rs.matrix
        self.parity_matrix = np.ascontiguousarray(
            self._rs.parity_matrix, dtype=np.uint8)
        self._parity_key = (self.parity_matrix.shape,
                            self.parity_matrix.tobytes())
        self.max_batch = max_batch if max_batch is not None else _env_int(
            "SEAWEEDFS_TPU_EC_SERVICE_BATCH", 16)
        self.max_queue = max_queue if max_queue is not None else _env_int(
            "SEAWEEDFS_TPU_EC_SERVICE_QUEUE", 64)
        self.max_batch_bytes = (
            max_batch_mb if max_batch_mb is not None else _env_int(
                "SEAWEEDFS_TPU_EC_SERVICE_BATCH_MB", 64)) << 20
        self.coalesce_bytes = (
            coalesce_kb if coalesce_kb is not None else _env_int(
                "SEAWEEDFS_TPU_EC_SERVICE_COALESCE_KB", 16)) << 10
        self._mesh = mesh
        self._q: deque[_Job] = deque()
        self._cond = threading.Condition()
        self._open = True
        self._thread: "threading.Thread | None" = None
        self._thread_err: "Exception | None" = None
        # reused input slab for host coalescing (scheduler-thread-only):
        # a fresh np.empty per batch pays more in page faults than the
        # kernel call it feeds (measured 0.47s build vs 0.15s compute)
        self._slab_in: "np.ndarray | None" = None
        # metric children resolved once — the submit/deliver hot path
        # must not pay registry locks per job
        self._depth_child = EC_SERVICE_QUEUE_DEPTH.labels()
        self._inflight_child = EC_SERVICE_INFLIGHT.labels()
        self._batch_jobs_child = EC_SERVICE_BATCH_JOBS.labels()
        self._batch_bytes_child = EC_SERVICE_BATCH_BYTES.labels()
        self._job_ok = {k: EC_SERVICE_JOBS.labels(k, "ok")
                        for k in ("parity", "apply")}
        self._job_err = {k: EC_SERVICE_JOBS.labels(k, "error")
                         for k in ("parity", "apply")}
        self._job_secs = {k: EC_SERVICE_JOB_SECONDS.labels(k)
                          for k in ("parity", "apply")}
        self._flush_children = {r: EC_SERVICE_FLUSH.labels(r)
                                for r in ("full", "bytes", "ready", "drain")}

    # -- submission -------------------------------------------------------

    def submit_parity(self, data, out=None) -> CodecFuture:
        """(data_shards, W) -> future of the parity rows."""
        return self._submit_many(
            "parity", self.parity_matrix, self._parity_key,
            (data,), (out,))[0]

    def submit_parity_many(self, datas, outs=None) -> list[CodecFuture]:
        """Vectored submit: one lock/wakeup for a group of parity jobs —
        callers with a batch of independent segments in hand (the mmap
        encoder) pay the queue overhead once, not per segment."""
        if outs is None:
            outs = (None,) * len(datas)
        return self._submit_many(
            "parity", self.parity_matrix, self._parity_key, datas, outs)

    def submit_apply(self, rows: np.ndarray, inputs, out=None) -> CodecFuture:
        """Arbitrary (R, S) GF matrix x S input rows -> future of R rows."""
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        if rows.ndim != 2:
            raise ValueError("rows must be a 2-D GF matrix")
        return self._submit_many(
            "apply", rows, (rows.shape, rows.tobytes()), (inputs,), (out,))[0]

    def submit_apply_many(self, rows: np.ndarray, inputs_list,
                          outs=None) -> list[CodecFuture]:
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        if rows.ndim != 2:
            raise ValueError("rows must be a 2-D GF matrix")
        if outs is None:
            outs = (None,) * len(inputs_list)
        return self._submit_many(
            "apply", rows, (rows.shape, rows.tobytes()), inputs_list, outs)

    @staticmethod
    def _validate(data, s: int):
        """-> (data, width).  2-D uint8 arrays pass through untouched
        (the fast path); anything else becomes a list of equal-length
        1-D uint8 rows."""
        if isinstance(data, np.ndarray) and data.ndim == 2:
            if data.shape[0] != s:
                raise ValueError(f"want {s} input rows, got {data.shape[0]}")
            if data.dtype != np.uint8:
                raise ValueError("inputs must be uint8")
            if not data.flags["C_CONTIGUOUS"]:
                data = np.ascontiguousarray(data)
            return data, data.shape[1]
        # ascontiguousarray, not asarray: the host fast path hands raw
        # row pointers to the native kernel, which reads stride-1 — a
        # strided view here would silently decode garbage
        data = [np.ascontiguousarray(r_, dtype=np.uint8) for r_ in data]
        if len(data) != s:
            raise ValueError(f"want {s} input rows, got {len(data)}")
        width = len(data[0])
        for r_ in data:
            if r_.ndim != 1 or len(r_) != width:
                raise ValueError("input rows must be equal-length 1-D")
        return data, width

    def _submit_many(self, kind, rows, key, datas, outs) -> list[CodecFuture]:
        r, s = rows.shape
        jobs: list[_Job] = []
        futs: list[CodecFuture] = []
        for data, out in zip(datas, outs):
            data, width = self._validate(data, s)
            if out is not None:
                out = list(out) if not isinstance(out, np.ndarray) else out
                if len(out) != r:
                    raise ValueError(f"want {r} output rows, got {len(out)}")
                for o in out:
                    if len(o) != width:
                        raise ValueError("output rows must match input width")
            job = _Job(kind, key, rows, data, width, out)
            futs.append(CodecFuture(job))
            if width == 0:  # nothing to compute: deliver inline
                job.result = (out if out is not None else
                              np.empty((r, 0), np.uint8))
                job.event.set()
            else:
                jobs.append(job)
        if jobs:
            with self._cond:
                if not self._open:
                    raise RuntimeError("codec service is closed")
                while len(self._q) >= self.max_queue:
                    self._cond.wait(0.1)
                    if not self._open:
                        raise RuntimeError("codec service is closed")
                self._q.extend(jobs)
                self._depth_child.set(len(self._q))
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._run, name="ec-codec-service",
                        daemon=True)
                    self._thread.start()
                self._cond.notify_all()
        return futs

    # -- sync conveniences ------------------------------------------------

    def parity_into(self, inputs, outs) -> None:
        self.submit_parity(inputs, out=outs).result()

    def apply_rows(self, rows, inputs):
        return self.submit_apply(rows, inputs).result()

    # -- lifecycle --------------------------------------------------------

    def close(self, timeout: "float | None" = 30.0) -> None:
        """Stop accepting jobs, drain everything in flight, stop the
        scheduler.  Every already-submitted job still gets its result."""
        with self._cond:
            self._open = False
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout)

    @property
    def closed(self) -> bool:
        return not self._open

    # -- scheduler --------------------------------------------------------

    def _collect_locked(self) -> "tuple[list[_Job], str]":
        """Pop the head job plus every queued job sharing its matrix, up
        to the job/byte caps.  Head-of-queue start = oldest-first, so no
        job class can starve another."""
        head = self._q.popleft()
        batch = [head]
        s = head.rows.shape[1]
        nbytes = head.width * s
        reason = "ready"
        if self.max_batch > 1 and self._q:
            kept: deque[_Job] = deque()
            while self._q:
                job = self._q.popleft()
                if job.key != head.key or job.kind != head.kind:
                    kept.append(job)
                    continue
                jb = job.width * s
                if len(batch) >= self.max_batch:
                    kept.append(job)
                    reason = "full"
                    break
                if nbytes + jb > self.max_batch_bytes:
                    kept.append(job)
                    reason = "bytes"
                    break
                batch.append(job)
                nbytes += jb
            kept.extend(self._q)
            self._q = kept
        self._depth_child.set(len(self._q))
        self._batch_jobs_child.observe(len(batch))
        self._batch_bytes_child.observe(nbytes)
        return batch, reason

    def _run(self) -> None:
        inflight: deque = deque()  # device mode: (jobs, device array)
        try:
            while True:
                with self._cond:
                    while not self._q and self._open and not inflight:
                        self._cond.wait(0.2)
                    batch = reason = None
                    if self._q:
                        batch, reason = self._collect_locked()
                        if not self._open and not self._q:
                            reason = "drain"
                    elif not inflight and not self._open:
                        break
                    self._cond.notify_all()  # wake blocked submitters
                if batch is None:
                    if inflight:
                        self._complete_device(*inflight.popleft())
                        self._inflight_child.set(len(inflight))
                    continue
                self._flush_children[reason].inc()
                try:
                    if self.mode == "device":
                        dev = self._dispatch_device(batch)
                        inflight.append((batch, dev))
                        self._inflight_child.set(len(inflight))
                        if len(inflight) >= 2:
                            self._complete_device(*inflight.popleft())
                            self._inflight_child.set(len(inflight))
                    else:
                        self._compute_host(batch)
                except Exception as e:
                    # the collected batch is in neither queue nor
                    # inflight — fail it here or its waiters hang forever
                    for job in batch:
                        self._fail(job, e)
                    raise
            while inflight:
                self._complete_device(*inflight.popleft())
                self._inflight_child.set(len(inflight))
        except Exception as e:  # scheduler death must not strand waiters
            self._thread_err = e
            for jobs, _dev in inflight:
                for job in jobs:
                    self._fail(job, e)
            with self._cond:
                pending = list(self._q)
                self._q.clear()
                self._open = False
                self._cond.notify_all()
            for job in pending:
                self._fail(job, e)

    # -- delivery ---------------------------------------------------------

    def _deliver(self, job: _Job, result, direct: bool = False) -> None:
        """``result`` is (R, W) array-like; ``direct`` means the compute
        already wrote the caller's ``out`` buffers."""
        if job.out is not None and not direct:
            for dst, src in zip(job.out, result):
                np.copyto(np.asarray(dst), src, casting="no")
            job.result = job.out
        else:
            job.result = result
        job.event.set()
        self._job_ok[job.kind].inc()
        self._job_secs[job.kind].observe(time.perf_counter() - job.t_submit)

    def _fail(self, job: _Job, err: Exception) -> None:
        if job.event.is_set():
            return
        job.error = err
        job.event.set()
        self._job_err[job.kind].inc()

    # -- host backend -----------------------------------------------------

    @staticmethod
    def _rows_of(data, s: int) -> list:
        return [data[i] for i in range(s)] if isinstance(
            data, np.ndarray) else data

    def _compute_host(self, batch: list[_Job]) -> None:
        from ..native import lib as native

        rows = batch[0].rows
        r, s = rows.shape
        use_native = native.available()
        mbytes = rows.tobytes()
        try:
            small = (len(batch) > 1
                     and all(j.width <= self.coalesce_bytes for j in batch))
            if small and use_native:
                # column-concatenate into the reused input slab -> ONE
                # kernel call for the whole batch; per-job results are
                # views of one output slab
                with _STAGE_BUILD.time():
                    total = sum(j.width for j in batch)
                    slab = self._slab_in
                    if (slab is None or slab.shape[0] != s
                            or slab.shape[1] < total):
                        slab = np.empty(
                            (s, max(total, 1 << 20)), dtype=np.uint8)
                        self._slab_in = slab
                    at = 0
                    for j in batch:
                        w = j.width
                        if isinstance(j.data, np.ndarray):
                            slab[:, at:at + w] = j.data
                        else:
                            for ri in range(s):
                                slab[ri, at:at + w] = j.data[ri]
                        at += w
                with _STAGE_COMPUTE.time():
                    out_slab = np.empty((r, total), dtype=np.uint8)
                    # row pointers: slab rows are strided by capacity, so
                    # pass each row's view; the kernel reads `total` bytes
                    native.gf_apply_fast(
                        mbytes, r, s,
                        [slab[i] for i in range(s)],
                        [out_slab[i] for i in range(r)], total)
                at = 0
                for j in batch:
                    self._deliver(j, out_slab[:, at:at + j.width])
                    at += j.width
                return
            with _STAGE_COMPUTE.time():
                for j in batch:
                    w = j.width
                    rows_in = self._rows_of(j.data, s)
                    direct = False
                    if not use_native:
                        out_arr = self._rs._apply(j.rows, [
                            np.ascontiguousarray(x) for x in rows_in])
                    else:
                        if (j.out is not None
                                and all(isinstance(o, np.ndarray)
                                        and o.dtype == np.uint8
                                        and o.flags["C_CONTIGUOUS"]
                                        for o in j.out)):
                            out_rows = list(j.out)
                            direct = True
                        else:
                            out_arr = np.empty((r, w), dtype=np.uint8)
                            out_rows = [out_arr[i] for i in range(r)]
                        native.gf_apply_fast(
                            mbytes, r, s, rows_in, out_rows, w)
                        if direct:
                            out_arr = out_rows
                    self._deliver(j, out_arr, direct=direct)
        except Exception as e:
            for j in batch:
                self._fail(j, e)

    # -- device backend ---------------------------------------------------

    def _device_mesh(self):
        if self._mesh is None:
            from ..parallel.mesh import make_mesh

            self._mesh = make_mesh()
        return self._mesh

    @staticmethod
    def _pad_width(width: int, sp: int) -> int:
        """Bucket widths to powers of two (multiples of sp) so the jitted
        sharded program compiles once per bucket, not once per slice."""
        w = max(sp, 256)
        while w < width:
            w <<= 1
        return -(-w // sp) * sp

    def _dispatch_device(self, batch: list[_Job]):
        from ..parallel.mesh import batch_apply_sharded

        mesh = self._device_mesh()
        dp, sp = mesh.shape["dp"], mesh.shape["sp"]
        s = batch[0].rows.shape[1]
        with _STAGE_BUILD.time():
            w_pad = self._pad_width(max(j.width for j in batch), sp)
            v_pad = -(-len(batch) // dp) * dp
            block = np.zeros((v_pad, s, w_pad), dtype=np.uint8)
            for vi, j in enumerate(batch):
                if isinstance(j.data, np.ndarray):
                    block[vi, :, :j.width] = j.data
                else:
                    for ri in range(s):
                        block[vi, ri, :j.width] = j.data[ri]
        with _STAGE_COMPUTE.time():  # trace/enqueue (async): compile cost
            return batch_apply_sharded(mesh, batch[0].rows, block)

    def _complete_device(self, batch: list[_Job], dev) -> None:
        try:
            with _STAGE_READBACK.time():  # blocks until compute + D2H done
                out = np.asarray(dev)
            for vi, j in enumerate(batch):
                self._deliver(j, out[vi, :, :j.width])
        except Exception as e:
            for j in batch:
                self._fail(j, e)


# ---------------------------------------------------------------------------
# Process-wide singletons: every caller of the same backend shares one
# queue, which is the whole point — concurrency ACROSS volumes is what
# the scheduler turns into batch occupancy.
# ---------------------------------------------------------------------------

_SERVICES: dict[str, CodecService] = {}
_SERVICES_LOCK = threading.Lock()


def enabled() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_EC_SERVICE", "1").lower() not in (
        "0", "false", "off", "no")


def get_service(codec_name: str = "cpu") -> "CodecService | None":
    """The shared service for a codec backend, or None when disabled."""
    if not enabled():
        return None
    key = "device" if codec_name in _DEVICE_CODECS else "host"
    with _SERVICES_LOCK:
        svc = _SERVICES.get(key)
        if svc is None or svc.closed:
            svc = CodecService(mode="auto", codec_name=(
                codec_name if key == "device" else "cpu"))
            _SERVICES[key] = svc
        return svc


def service_for_codec(codec_name: str) -> "CodecService | None":
    """Default routing for the bulk encode/rebuild pipelines: device
    codecs go through the service ONLY when the fast probe confirms a
    reachable accelerator (otherwise the direct host paths — mmap encode,
    inline SIMD rebuild — are already optimal for one volume and the
    per-volume device path keeps its tested direct dispatch).  Callers
    that KNOW they are concurrent (bench --service, batch flows) pass an
    explicit service instead."""
    if not enabled() or codec_name not in _DEVICE_CODECS:
        return None
    if not device_probe.probe().accelerator:
        return None
    return get_service(codec_name)


def service_for_degraded() -> "CodecService | None":
    """Host-mode service for per-needle degraded reads (which must never
    pay a device dispatch).  Opt-in: a lone read pays one extra thread
    hop, so this is for hosts expecting degraded-read storms."""
    if not enabled():
        return None
    if os.environ.get(
            "SEAWEEDFS_TPU_EC_SERVICE_DEGRADED", "0").lower() in (
            "0", "false", "off", "no"):
        return None
    return get_service("cpu")


def shutdown_all(timeout: "float | None" = 30.0) -> None:
    """Drain and close every shared service (server shutdown, tests).
    Safe to call repeatedly; a later get_service starts a fresh one."""
    with _SERVICES_LOCK:
        svcs = list(_SERVICES.values())
        _SERVICES.clear()
    for svc in svcs:
        svc.close(timeout)
