"""GF(2^8) arithmetic and Reed-Solomon generator-matrix construction.

The field is GF(2^8) with the reduction polynomial x^8+x^4+x^3+x^2+1 (0x11D)
and generator element 2 — the same field used by the Backblaze/klauspost
Reed-Solomon lineage that the reference depends on
(reference: go.mod:52 `github.com/klauspost/reedsolomon v1.9.2`, called from
weed/storage/erasure_coding/ec_encoder.go:198).  The generator matrix here is
constructed with the identical algorithm (Vandermonde rows `r^c`, then
normalised so the top square is the identity) so that parity output is
byte-identical to the reference codec.

Everything in this module is plain numpy on the host: matrices involve at
most 14x10 elements.  Bulk byte throughput lives in rs_cpu.py (numpy/C++
codec) and rs_jax.py (TPU codec); the one piece of THIS module that a storm
of degraded reads hammers is decode_matrix_for, whose inversion result is
therefore cached per survivor set.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict

import numpy as np

FIELD_SIZE = 256
POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1


def _generate_tables() -> tuple[np.ndarray, np.ndarray]:
    """Build exp/log tables for GF(2^8) with generator 2."""
    exp = np.zeros(255, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    log[0] = -1  # undefined; never read for 0
    return exp, log


EXP_TABLE, LOG_TABLE = _generate_tables()


@functools.cache
def mul_table() -> np.ndarray:
    """Full 256x256 GF multiplication table (64KB), uint8."""
    la = LOG_TABLE[np.arange(256, dtype=np.int32)]
    # t[a, b] = exp[(log a + log b) % 255], 0 if either is 0
    s = (la[:, None] + la[None, :]) % 255
    t = EXP_TABLE[s]  # fancy indexing allocates the fresh table
    t[0, :] = 0
    t[:, 0] = 0
    return t


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) + int(LOG_TABLE[b])) % 255])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) - int(LOG_TABLE[b])) % 255])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_exp(a: int, n: int) -> int:
    """a**n in GF(2^8), matching the reference codec's galExp semantics:
    n==0 -> 1 (even for a==0); a==0 -> 0 otherwise."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % 255])


# ---------------------------------------------------------------------------
# Matrix algebra over GF(2^8).  Matrices are small numpy uint8 2-D arrays.
# ---------------------------------------------------------------------------


def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF matrix product (small matrices, host side)."""
    assert a.shape[1] == b.shape[0]
    t = mul_table()
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[0]):
        # XOR-accumulate products of row i with every column
        prods = t[a[i][:, None], b]  # (k, n)
        out[i] = np.bitwise_xor.reduce(prods, axis=0)
    return out


def mat_identity(n: int) -> np.ndarray:
    m = np.zeros((n, n), dtype=np.uint8)
    np.fill_diagonal(m, 1)
    return m


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination."""
    n = m.shape[0]
    assert m.shape == (n, n)
    work = np.concatenate([m.astype(np.uint8), mat_identity(n)], axis=1)
    t = mul_table()
    for col in range(n):
        # pivot
        if work[col, col] == 0:
            for r in range(col + 1, n):
                if work[r, col] != 0:
                    work[[col, r]] = work[[r, col]]
                    break
            else:
                raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        pivot = int(work[col, col])
        if pivot != 1:
            inv_p = gf_inv(pivot)
            work[col] = t[inv_p, work[col]]
        for r in range(n):
            if r != col and work[r, col] != 0:
                factor = int(work[r, col])
                work[r] ^= t[factor, work[col]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """vm[r, c] = r**c in GF(2^8) — the reference codec's starting matrix."""
    vm = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            vm[r, c] = gf_exp(r, c)
    return vm


@functools.cache
def rs_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """The (total x data) encoding matrix whose top square is the identity.

    This reproduces the reference codec's default matrix (Vandermonde
    normalised by the inverse of its top square), so parity shards are
    byte-identical to the klauspost/reedsolomon output consumed by
    weed/storage/erasure_coding.
    """
    vm = vandermonde(total_shards, data_shards)
    top_inv = mat_inv(vm[:data_shards])
    m = mat_mul(vm, top_inv)
    m.setflags(write=False)
    return m


@functools.cache
def rs_parity_matrix(data_shards: int, parity_shards: int) -> np.ndarray:
    """Just the parity rows: (parity x data)."""
    m = rs_matrix(data_shards, data_shards + parity_shards)
    p = m[data_shards:].copy()
    p.setflags(write=False)
    return p


@functools.cache
def cauchy_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """Cauchy-style alternative (the reference codec's WithCauchyMatrix option)."""
    m = np.zeros((total_shards, data_shards), dtype=np.uint8)
    m[:data_shards] = mat_identity(data_shards)
    for r in range(data_shards, total_shards):
        for c in range(data_shards):
            m[r, c] = gf_inv(r ^ c)
    m.setflags(write=False)
    return m


def decode_matrix_for(
    matrix: np.ndarray, data_shards: int, present: list[int]
) -> np.ndarray:
    """Given >=data_shards present shard row indices, return the (data x data)
    matrix that maps the first `data_shards` present shards back to the data
    shards.  Rows of `matrix` correspond to shard ids.

    A thin view over decode_plan_for (wanted = every data shard), so the
    inversion is shared with every other consumer of the plan cache."""
    return decode_plan_for(
        matrix, data_shards, present, tuple(range(data_shards)))


def decode_plan_for(
    matrix: np.ndarray,
    data_shards: int,
    present: "list[int] | tuple[int, ...]",
    wanted: "list[int] | tuple[int, ...]",
) -> np.ndarray:
    """The (len(wanted) x data_shards) GF matrix mapping the FIRST
    `data_shards` present shards to the `wanted` shard ids — the whole
    decode program for one survivor set, inversion and parity-row
    composition included.

    Cached per (matrix, survivor set, wanted set) behind one lock: a
    degraded-read storm reconstructs thousands of intervals against the
    SAME missing shards, and the 10x10 GF inversion (plus, for parity
    targets, a GF row-by-matrix product per call) was the hottest single
    function in that profile.  The cache is a bounded LRU — the full
    RS(10,4) space is C(14,10) survivor sets x a handful of wanted sets,
    so steady state is all hits; rs_cpu, rs_jax and the rebuild pipeline
    all share it.  Hit/miss rates are exported as
    seaweedfs_ec_decode_plan_total{result}.
    """
    if len(present) < data_shards:
        raise ValueError(
            f"need {data_shards} shards to decode, have {len(present)}"
        )
    sources = tuple(present[:data_shards])
    key = (matrix.shape, matrix.tobytes(), sources, tuple(wanted))
    with _PLAN_LOCK:
        cached = _PLAN_CACHE.get(key)
        if cached is not None:
            _PLAN_CACHE.move_to_end(key)
            _plan_metric("hit")
            return cached
    _plan_metric("miss")
    rows = matrix[np.asarray(sources, dtype=np.int64)]
    dec = mat_inv(rows)
    plan = np.empty((len(wanted), data_shards), dtype=np.uint8)
    for i, w in enumerate(wanted):
        if w < data_shards:
            plan[i] = dec[w]
        else:
            # parity row composed through the decode matrix (GF product)
            plan[i] = mat_mul(matrix[w:w + 1, :data_shards], dec)[0]
    plan.setflags(write=False)
    with _PLAN_LOCK:
        _PLAN_CACHE[key] = plan
        while len(_PLAN_CACHE) > _PLAN_CACHE_MAX:
            _PLAN_CACHE.popitem(last=False)
    return plan


# >= C(14,10)=1001 survivor sets x the few wanted-sets each sees in
# practice; LRU so a long-lived server with exotic shard geometries can
# never grow without bound
_PLAN_CACHE_MAX = 4096
_PLAN_CACHE: OrderedDict[tuple, np.ndarray] = OrderedDict()
_PLAN_LOCK = threading.Lock()


def _plan_metric(result: str) -> None:
    # lazy: keeps gf256 importable (and the tables usable) even if the
    # stats package is mid-import on some exotic path
    global _PLAN_HIT, _PLAN_MISS
    if _PLAN_HIT is None:
        try:
            from ..stats.metrics import EC_DECODE_PLAN

            _PLAN_HIT = EC_DECODE_PLAN.labels("hit")
            _PLAN_MISS = EC_DECODE_PLAN.labels("miss")
        except ImportError:  # pragma: no cover
            return
    (_PLAN_HIT if result == "hit" else _PLAN_MISS).inc()


_PLAN_HIT = None
_PLAN_MISS = None


def bit_matrix(matrix: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix (R, C) into its GF(2) bit form (8R, 8C).

    Output bit k of output byte i is the XOR over input bytes j and input bits
    l of  A[8i+k, 8j+l] & input_bit[j, l],  where
    A[8i+k, 8j+l] = bit k of (matrix[i, j] * 2^l).

    This is what turns the GF matmul into a plain integer matmul (+ parity) on
    the TPU MXU: unpack bytes to bits, int8 matmul with A, take &1, repack.
    """
    r, c = matrix.shape
    t = mul_table()
    a = np.zeros((8 * r, 8 * c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            g = int(matrix[i, j])
            for l in range(8):
                prod = int(t[g, (1 << l)])
                for k in range(8):
                    a[8 * i + k, 8 * j + l] = (prod >> k) & 1
    return a
