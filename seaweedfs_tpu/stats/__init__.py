"""Prometheus-format metrics registry + exposition endpoint.

Reference surface: weed/stats/metrics.go:25-123.
"""

from .metrics import (
    Counter,
    EC_BYTES_HISTOGRAM,
    EC_OP_HISTOGRAM,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    REQUEST_COUNTER,
    REQUEST_HISTOGRAM,
    serve_metrics,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY", "serve_metrics",
    "EC_BYTES_HISTOGRAM", "EC_OP_HISTOGRAM",
    "REQUEST_COUNTER", "REQUEST_HISTOGRAM",
]
