"""Prometheus-format metrics registry + exposition endpoint.

Reference surface: weed/stats/metrics.go:25-123.
"""

from .metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    REGISTRY,
    serve_metrics,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry", "REGISTRY", "serve_metrics",
]
