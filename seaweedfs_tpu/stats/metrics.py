"""A small Prometheus client: counters, gauges, histograms with labels,
text exposition on a /metrics HTTP endpoint.

Reference: weed/stats/metrics.go — the same metric families (request
counters + latency histograms per server/operation, volume/EC-shard
gauges), exposed on -metricsPort or pushed to a gateway.  No external
prometheus_client dependency: the exposition format is a stable text
protocol worth owning.
"""

from __future__ import annotations

import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..util.httpd import FrameworkHTTPServer

_DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# exemplar rotation window: each histogram bucket remembers the SLOWEST
# recent observation's trace id for this long before a smaller sample may
# replace it — long enough for an alert evaluation tick to pick it up,
# short enough that a page links to the incident, not last week's spike
EXEMPLAR_WINDOW_S = float(
    os.environ.get("SEAWEEDFS_TPU_EXEMPLAR_WINDOW_S", "60"))

_FAMILY_RE = re.compile(r"^[A-Za-z_:][A-Za-z0-9_:]*$")


def parse_family_prefixes(raw: str) -> list[str] | None:
    """Validated `?family=<prefix>[,<prefix>...]` filter shared by every
    /metrics endpoint and the master's /cluster/metrics.  Empty -> None
    (no filter); malformed -> ValueError with an operator-readable
    message (a typo'd filter silently matching nothing would read as
    'cluster emits no metrics' mid-incident)."""
    raw = (raw or "").strip()
    if not raw:
        return None
    prefixes = [p.strip() for p in raw.split(",") if p.strip()]
    if not prefixes:
        return None
    if len(prefixes) > 16:
        raise ValueError("family: at most 16 comma-separated prefixes")
    for p in prefixes:
        if not _FAMILY_RE.match(p):
            raise ValueError(
                f"family prefix {p!r} must match [A-Za-z_:][A-Za-z0-9_:]*")
    return prefixes


def escape_label_value(v: str) -> str:
    """Prometheus text exposition: label values escape \\, \" and newline."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def format_le(bound: float) -> str:
    """Render a bucket bound as a float consistently (`10.0`, not `10`),
    so scrapers that string-match bounds see one canonical spelling."""
    return repr(float(bound))


# families whose label cardinality scales with the environment (one child
# per peer / data dir / hot key) — emitted LAST from snapshot_samples so
# they can never crowd the fixed-cardinality families SLO rules read out
# of the 512-sample heartbeat snapshot fallback
SNAPSHOT_DENY_PREFIXES = (
    "seaweedfs_connpool_in_use",
    "seaweedfs_connpool_idle",
    "seaweedfs_disk_free_bytes",
    "seaweedfs_disk_total_bytes",
    "seaweedfs_disk_state",
    "seaweedfs_hotkey_",
)


class Metric:
    def __init__(self, name: str, help_: str, label_names: tuple[str, ...]):
        self.name = name
        self.help = help_
        self.label_names = label_names
        self._children: dict[tuple, object] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str):
        key = tuple(str(v) for v in values)
        if len(key) != len(self.label_names):
            raise ValueError(
                f"{self.name}: want {len(self.label_names)} labels, got {len(key)}"
            )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _label_str(self, key: tuple) -> str:
        if not key:
            return ""
        pairs = ",".join(
            f'{n}="{escape_label_value(v)}"'
            for n, v in zip(self.label_names, key)
        )
        return "{" + pairs + "}"


class _CounterChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount


class Counter(Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            out.append(f"{self.name}{self._label_str(key)} {child.value}")
        return out


class _GaugeChild:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


class Gauge(Counter):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, v: float) -> None:
        self.labels().set(v)


class _HistogramChild:
    __slots__ = ("buckets", "counts", "total", "count", "exemplars",
                 "_lock")

    def __init__(self, buckets):
        self.buckets = buckets
        self.counts = [0] * len(buckets)
        self.total = 0.0
        self.count = 0
        # bucket index (len(buckets) = +Inf) -> [value, trace_id, wall_ts]
        # of the slowest observation in the current exemplar window
        self.exemplars: dict[int, list] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, trace_id: str | None = None) -> None:
        with self._lock:
            self.total += v
            self.count += 1
            idx = len(self.buckets)
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    idx = min(idx, i)
            if trace_id:
                cur = self.exemplars.get(idx)
                now = time.time()
                # keep the slowest sample per bucket, but let it rotate:
                # a stale all-time max would pin a page's exemplar to an
                # incident long resolved
                if (cur is None or v >= cur[0]
                        or now - cur[2] > EXEMPLAR_WINDOW_S):
                    self.exemplars[idx] = [v, trace_id, now]

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets=_DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, v: float, trace_id: str | None = None) -> None:
        self.labels().observe(v, trace_id=trace_id)

    def exemplars(self) -> list[dict]:
        """Per-bucket slowest-sample exemplars across every child:
        [{labels, le, value, traceId, ageSeconds}], newest-window data
        only (entries older than 2x the window are dropped — the alert
        that wants them has already evaluated)."""
        now = time.time()
        with self._lock:
            items = list(self._children.items())
        out: list[dict] = []
        for key, child in items:
            with child._lock:
                entries = [(i, list(e)) for i, e in child.exemplars.items()]
            for idx, (value, trace_id, ts) in entries:
                age = now - ts
                if age > 2 * EXEMPLAR_WINDOW_S:
                    continue
                le = (format_le(self.buckets[idx])
                      if idx < len(self.buckets) else "+Inf")
                out.append({
                    "family": self.name,
                    "labels": dict(zip(self.label_names, key)),
                    "le": le,
                    "value": round(value, 6),
                    "traceId": trace_id,
                    "ageSeconds": round(age, 3),
                })
        return out

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        with self._lock:
            items = list(self._children.items())
        for key, child in items:
            base = dict(zip(self.label_names, key))
            for b, c in zip(child.buckets, child.counts):
                labels = {**base, "le": format_le(b)}
                pairs = ",".join(
                    f'{n}="{escape_label_value(v)}"'
                    for n, v in labels.items()
                )
                out.append(f"{self.name}_bucket{{{pairs}}} {c}")
            inf_pairs = ",".join(
                f'{n}="{escape_label_value(v)}"'
                for n, v in {**base, "le": "+Inf"}.items()
            )
            out.append(f"{self.name}_bucket{{{inf_pairs}}} {child.count}")
            ls = self._label_str(key)
            out.append(f"{self.name}_sum{ls} {child.total}")
            out.append(f"{self.name}_count{ls} {child.count}")
        return out


class Registry:
    def __init__(self):
        self._metrics: dict[str, Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "", labels: tuple = ()) -> Counter:
        return self._get_or_make(Counter, name, help_, tuple(labels))

    def gauge(self, name: str, help_: str = "", labels: tuple = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help_, tuple(labels))

    def histogram(self, name: str, help_: str = "", labels: tuple = (),
                  buckets=_DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, tuple(labels), buckets)
                self._metrics[name] = m
            elif (type(m) is not Histogram
                  or m.label_names != tuple(labels)):
                raise ValueError(self._conflict(name, m))
            return m

    def _get_or_make(self, cls, name, help_, labels):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help_, labels)
                self._metrics[name] = m
            elif type(m) is not cls or m.label_names != labels:
                # two call sites disagreeing about a family is a bug that
                # silently corrupts one of them — fail at import, loudly
                raise ValueError(self._conflict(name, m))
            return m

    @staticmethod
    def _conflict(name: str, existing: Metric) -> str:
        return (f"metric family {name!r} already registered as "
                f"{existing.kind} with labels {existing.label_names}; "
                "register every family exactly once (stats/metrics.py)")

    def family(self, name: str) -> "Metric | None":
        """The registered family, trying histogram base names too (so
        `foo_seconds_bucket` resolves to the `foo_seconds` histogram)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                return m
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    m = self._metrics.get(name[: -len(suffix)])
                    if m is not None and m.kind == "histogram":
                        return m
        return None

    def render(self, family_prefixes: "list[str] | None" = None) -> str:
        """Text exposition; `family_prefixes` (from ?family=) restricts
        the output to families whose name starts with any prefix — the
        SLO engine and operators scrape a subset instead of the full
        exposition on every evaluation tick."""
        with self._lock:
            metrics = list(self._metrics.values())
        if family_prefixes is not None:
            metrics = [m for m in metrics
                       if any(m.name.startswith(p) for p in family_prefixes)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def exemplars(self, family_prefix: str = "") -> list[dict]:
        """Histogram exemplars (slowest recent sample per bucket) for
        families matching the prefix, slowest first — the trace ids a
        firing latency alert embeds so /cluster/alerts links straight to
        /cluster/traces."""
        with self._lock:
            metrics = [m for m in self._metrics.values()
                       if m.kind == "histogram"
                       and m.name.startswith(family_prefix)]
        out: list[dict] = []
        for m in metrics:
            out.extend(m.exemplars())
        out.sort(key=lambda e: e["value"], reverse=True)
        return out[:32]

    def snapshot_samples(self, max_samples: int = 512) -> list:
        """-> [(exposition sample name incl. labels, float value)] for
        every counter and gauge child — the compact stats snapshot a
        heartbeat carries to the master (federation's fallback for nodes
        a live scrape cannot reach).  Histograms are skipped: their
        bucket fan-out would dwarf the beat for tail-latency data the
        live scrape serves better."""
        with self._lock:
            metrics = list(self._metrics.values())
        # three emission tiers under the cap:
        #   0: geo-link + listener health — they ride ONLY this snapshot
        #      to /cluster/geo (a dead cluster cannot be scraped live);
        #      tiny families, but registered late, so without the boost a
        #      high-cardinality node would push them past the cap
        #   1: everything else, including the families SLO rules read
        #      from the snapshot fallback
        #   2: deny-listed high-cardinality families (per-peer connpool,
        #      per-dir disk, per-key hot-key tables) — one busy node can
        #      mint hundreds of children here, and before the deny-list
        #      they could evict the tier-1 families alerts depend on
        metrics.sort(key=lambda m: (
            0 if m.name.startswith(("seaweedfs_geo_",
                                    "seaweedfs_meta_listener_"))
            else 2 if m.name.startswith(SNAPSHOT_DENY_PREFIXES)
            else 1))
        out = []
        for m in metrics:
            if m.kind not in ("counter", "gauge"):
                continue
            with m._lock:
                items = list(m._children.items())
            for key, child in items:
                out.append((f"{m.name}{m._label_str(key)}",
                            float(child.value)))
                if len(out) >= max_samples:
                    return out
        return out


REGISTRY = Registry()

# the reference's metric families (stats/metrics.go:25-123)
REQUEST_COUNTER = REGISTRY.counter(
    "seaweedfs_request_total", "requests by server type and operation",
    labels=("type", "op"),
)
REQUEST_HISTOGRAM = REGISTRY.histogram(
    "seaweedfs_request_seconds", "request latency", labels=("type", "op"),
)
VOLUME_GAUGE = REGISTRY.gauge(
    "seaweedfs_volumes", "volumes hosted, by collection and kind",
    labels=("collection", "type"),
)
DISK_SIZE_GAUGE = REGISTRY.gauge(
    "seaweedfs_disk_size_bytes", "stored bytes by collection and kind",
    labels=("collection", "type"),
)
CHUNK_CACHE_COUNTER = REGISTRY.counter(
    "seaweedfs_chunk_cache_total", "chunk cache lookups by result",
    labels=("result",),
)

# disk-fault survival plane (storage/disk_health.py): per-data-directory
# statvfs watermarks + the health state machine every classified write
# error feeds.  `state` is numeric-coded (0 healthy, 1 low_space, 2 full,
# 3 failing) so one gauge family tells an alert rule everything.
DISK_FREE_GAUGE = REGISTRY.gauge(
    "seaweedfs_disk_free_bytes", "free bytes on a data directory's filesystem",
    labels=("dir",),
)
DISK_TOTAL_GAUGE = REGISTRY.gauge(
    "seaweedfs_disk_total_bytes",
    "total bytes on a data directory's filesystem",
    labels=("dir",),
)
DISK_STATE_GAUGE = REGISTRY.gauge(
    "seaweedfs_disk_state",
    "disk health state (0=healthy 1=low_space 2=full 3=failing)",
    labels=("dir",),
)
DISK_WRITE_ERROR = REGISTRY.counter(
    "seaweedfs_disk_write_errors_total",
    "classified storage-write failures by kind",
    labels=("kind",),  # enospc | eio | short | other
)
VOLUME_FULL_REJECT = REGISTRY.counter(
    "seaweedfs_volume_full_rejects_total",
    "writes rejected with the typed volume-full (409) error",
)
DISK_EVACUATE_COUNTER = REGISTRY.counter(
    "seaweedfs_disk_evacuations_total",
    "proactive failing-disk evacuation moves by kind and outcome",
    labels=("kind", "result"),  # kind: ec_shard|volume; result: ok|error
)

# keep-alive connection pool (util/connpool.py): every internal HTTP hop
# either reuses a pooled socket or pays a fresh dial; evictions count
# sockets dropped for staleness, pool overflow, or a dead keep-alive
CONNPOOL_REUSE = REGISTRY.counter(
    "seaweedfs_connpool_reuse_total",
    "internal HTTP requests served on a reused pooled connection",
)
CONNPOOL_DIAL = REGISTRY.counter(
    "seaweedfs_connpool_dial_total",
    "fresh TCP dials made by the connection pool",
)
CONNPOOL_EVICT = REGISTRY.counter(
    "seaweedfs_connpool_evict_total",
    "pooled connections discarded (idle-expired, overflow, or dead)",
)

# hot-needle cache on the volume-server read path
NEEDLE_CACHE_HIT = REGISTRY.counter(
    "seaweedfs_needle_cache_hit_total", "needle reads served from cache",
)
NEEDLE_CACHE_MISS = REGISTRY.counter(
    "seaweedfs_needle_cache_miss_total", "needle reads that missed the cache",
)
NEEDLE_CACHE_EVICT = REGISTRY.counter(
    "seaweedfs_needle_cache_evict_total",
    "needles evicted from the cache by the byte bound",
)

REPLICATION_ERROR = REGISTRY.counter(
    "seaweedfs_replication_error_total",
    "replica fan-out failures by operation",
    labels=("op",),
)

# EC codec telemetry: encode/reconstruct wall time and bytes moved per
# call, labeled by op and backend impl (cpu / xor / mxu / pallas) so the
# rebuild-traffic cost the warehouse-cluster study flags is attributable
EC_OP_HISTOGRAM = REGISTRY.histogram(
    "seaweedfs_ec_op_seconds", "EC codec operation latency",
    labels=("op", "impl"),
)
_EC_BYTE_BUCKETS = tuple(float(4 ** k) for k in range(5, 16))  # 1KB..1GB
EC_BYTES_HISTOGRAM = REGISTRY.histogram(
    "seaweedfs_ec_op_bytes", "bytes processed per EC codec operation",
    labels=("op", "impl"), buckets=_EC_BYTE_BUCKETS,
)

# EC repair data plane: shard rebuilds (pipelined read->decode->write in
# storage/ec/encoder.rebuild_ec_files) and the degraded-read caches.
# Rebuild traffic dominating cluster I/O is the classic EC failure mode,
# so its cost and its cache effectiveness are first-class families.
EC_REBUILD_SECONDS = REGISTRY.histogram(
    "seaweedfs_ec_rebuild_seconds", "wall time per EC shard rebuild",
    labels=("impl",), buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
)
EC_REBUILD_BYTES = REGISTRY.counter(
    "seaweedfs_ec_rebuild_bytes_total",
    "source bytes consumed by EC shard rebuilds, by origin locality",
    labels=("source",),  # local (this node) | rack (same rack) | dc (beyond)
)

# partial-sum repair protocol (VolumeEcShardPartialApply): sources stream
# coefficient-weighted GF(2^8) sums instead of raw shard intervals, so
# rebuild ingress drops ~sources/racks-fold; `serve` counts bytes a
# source computed+streamed out, `recv` counts aggregated partial bytes a
# rebuilder/aggregator pulled in
EC_PARTIAL_BYTES = REGISTRY.counter(
    "seaweedfs_ec_partial_bytes_total",
    "partial-sum repair bytes by direction",
    labels=("op",),  # serve | recv
)
EC_PARTIAL_JOBS = REGISTRY.counter(
    "seaweedfs_ec_partial_jobs_total",
    "partial-sum repair requests by role and outcome",
    labels=("kind", "result"),  # kind: serve|fetch; result: ok|error
)
EC_PARTIAL_FALLBACK = REGISTRY.counter(
    "seaweedfs_ec_partial_fallback_total",
    "partial-sum repairs that degraded to the full-shard fetch path",
    labels=("path",),  # rebuild | degraded
)
EC_REBUILD_SHARDS = REGISTRY.counter(
    "seaweedfs_ec_rebuild_shards_total", "shard files reconstructed",
)
EC_REBUILD_RESULT = REGISTRY.counter(
    "seaweedfs_ec_rebuild_total", "rebuild attempts by outcome",
    labels=("result",),  # ok | error
)

# decode-plan cache (ops/gf256.decode_plan_for): one GF matrix inversion
# per survivor set instead of one per slice / per degraded read
EC_DECODE_PLAN = REGISTRY.counter(
    "seaweedfs_ec_decode_plan_total", "decode-plan cache lookups by result",
    labels=("result",),  # hit | miss
)

# reconstructed-interval LRU + single-flight coalescing on the degraded
# read path (storage/ec/volume.py)
EC_INTERVAL_CACHE = REGISTRY.counter(
    "seaweedfs_ec_interval_cache_total",
    "reconstructed-interval cache lookups and evictions by result",
    labels=("result",),  # hit | miss | evict
)
EC_SINGLEFLIGHT = REGISTRY.counter(
    "seaweedfs_ec_singleflight_total",
    "degraded-read interval reconstructions by single-flight role",
    labels=("result",),  # leader | coalesced
)

# fault-tolerance layer (util/failsafe.py, util/faultpoint.py) — declared
# HERE so the metric-family lint can hold one file to "every family
# registered exactly once"; the consumers import these bindings
RETRY_COUNTER = REGISTRY.counter(
    "seaweedfs_retry_total",
    "retried failures by caller type, operation and failure reason",
    labels=("type", "op", "reason"),
)
CIRCUIT_STATE = REGISTRY.gauge(
    "seaweedfs_circuit_state",
    "per-peer circuit breaker state (0 closed, 1 open, 2 half-open)",
    labels=("peer",),
)
CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "seaweedfs_circuit_transitions_total",
    "circuit breaker state transitions by peer and target state",
    labels=("peer", "to"),
)
FAULT_COUNTER = REGISTRY.counter(
    "seaweedfs_fault_injected_total",
    "faults injected by point name",
    labels=("point",),
)

# -- raft consensus (master/raft.py) ----------------------------------------
# one gauge set per quorum member (`node` = ip:port) so a federated scrape
# of three masters shows term skew, commit lag and role at a glance; the
# leader-change counter is what the flap SLO pages on.

RAFT_TERM = REGISTRY.gauge(
    "seaweedfs_raft_term", "current raft term", labels=("node",),
)
RAFT_ROLE = REGISTRY.gauge(
    "seaweedfs_raft_role",
    "raft role (0 follower, 1 candidate, 2 leader)",
    labels=("node",),
)
RAFT_COMMIT_INDEX = REGISTRY.gauge(
    "seaweedfs_raft_commit_index", "highest committed log index",
    labels=("node",),
)
RAFT_LOG_ENTRIES = REGISTRY.gauge(
    "seaweedfs_raft_log_entries", "entries in the raft log",
    labels=("node",),
)
RAFT_LEADER_CHANGES = REGISTRY.counter(
    "seaweedfs_raft_leader_changes_total",
    "times this node gained or lost leadership",
    labels=("node",),
)
RAFT_RPC = REGISTRY.counter(
    "seaweedfs_raft_rpc_total",
    "outbound raft rpcs by type (vote|append) and result (ok|error|dropped)",
    labels=("type", "result"),
)
STALE_EPOCH_REJECTED = REGISTRY.counter(
    "seaweedfs_stale_epoch_rejected_total",
    "volume-server rpcs refused because they carried a deposed leader's "
    "epoch, by rpc method",
    labels=("method",),
)

# -- saturation telemetry (ISSUE 5 leg 3) -----------------------------------
# a stalled pool is invisible in throughput counters until the damage is
# done; queue depth + active workers make "which stage is the bottleneck"
# a PromQL query.  `executor` ∈ replica_fanout | ec_fetch | filer_chunk |
# ec_rebuild_read | federation (see util/executors.py call sites).

EXECUTOR_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_executor_queue_depth",
    "tasks submitted to a pool but not yet started",
    labels=("executor",),
)
EXECUTOR_ACTIVE = REGISTRY.gauge(
    "seaweedfs_executor_active_workers",
    "pool tasks currently executing",
    labels=("executor",),
)
EXECUTOR_MAX = REGISTRY.gauge(
    "seaweedfs_executor_max_workers",
    "pool worker capacity (saturation = active / max)",
    labels=("executor",),
)

# per-peer connection accounting for the keep-alive pool: in_use counts
# sockets checked out to in-flight requests, idle counts sockets parked
# in the pool.  in_use pinned at its ceiling = the peer is saturated.
CONNPOOL_IN_USE = REGISTRY.gauge(
    "seaweedfs_connpool_in_use",
    "pooled connections checked out to in-flight requests, per peer",
    labels=("peer",),
)
CONNPOOL_IDLE = REGISTRY.gauge(
    "seaweedfs_connpool_idle",
    "idle pooled connections, per peer",
    labels=("peer",),
)

# per-stage wall time inside the pipelined EC encode/rebuild (prefetch /
# decode / write threads): the pipeline runs at max(stages), so the
# widest histogram names the bottleneck
EC_PIPELINE_STAGE = REGISTRY.histogram(
    "seaweedfs_ec_pipeline_stage_seconds",
    "per-slice wall time in each EC encode/rebuild pipeline stage",
    labels=("stage",),  # prefetch | decode | write
)

# -- EC codec service (ops/codec_service.py) --------------------------------
# one bounded queue between every GF caller (encode, rebuild, degraded
# reads, bench) and the compute backend; the scheduler coalesces
# same-matrix jobs into batches.  Occupancy near 1 under load means the
# producers are not concurrent enough to batch; queue_depth pinned at the
# bound means the backend is the bottleneck (backpressure engaged).

EC_SERVICE_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_ec_service_queue_depth",
    "codec-service jobs submitted but not yet scheduled into a batch",
)
EC_SERVICE_INFLIGHT = REGISTRY.gauge(
    "seaweedfs_ec_service_inflight_batches",
    "codec-service batches dispatched to the device, results not yet read back",
)
EC_SERVICE_BATCH_JOBS = REGISTRY.histogram(
    "seaweedfs_ec_service_batch_jobs",
    "jobs coalesced into each codec-service batch (occupancy)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0),
)
EC_SERVICE_BATCH_BYTES = REGISTRY.histogram(
    "seaweedfs_ec_service_batch_bytes",
    "input bytes per codec-service batch",
    buckets=_EC_BYTE_BUCKETS,
)
EC_SERVICE_FLUSH = REGISTRY.counter(
    "seaweedfs_ec_service_flush_total",
    "codec-service batch flushes by trigger",
    labels=("reason",),  # full | bytes | ready | drain
)
EC_SERVICE_JOBS = REGISTRY.counter(
    "seaweedfs_ec_service_jobs_total",
    "codec-service jobs by kind and outcome",
    labels=("kind", "result"),  # parity|apply x ok|error
)
EC_SERVICE_JOB_SECONDS = REGISTRY.histogram(
    "seaweedfs_ec_service_job_seconds",
    "codec-service job wall time, submit to delivered result",
    labels=("kind",),
)
EC_SERVICE_STAGE = REGISTRY.histogram(
    "seaweedfs_ec_service_stage_seconds",
    "per-batch wall time in each codec-service stage",
    labels=("stage",),  # build | compute | readback
)


# -- filer fleet: ring routing + per-tenant admission (filer/fleet/) --------
# the sharded metadata plane: gateways route every metadata op through a
# consistent-hash ring over master-discovered filers; each filer enforces
# tenant quotas and WFQ admission.  route result `failover` means the
# owner was unreachable and a ring successor served (the shard-death
# path); sustained `failover` with no membership change = a dead filer
# the master has not dropped yet.

RING_NODES = REGISTRY.gauge(
    "seaweedfs_filer_ring_nodes",
    "filer shards in this process's current ring snapshot",
)
RING_REFRESH = REGISTRY.counter(
    "seaweedfs_filer_ring_refresh_total",
    "ring membership refreshes by trigger",
    labels=("trigger",),  # ttl | forced | error
)
RING_ROUTE = REGISTRY.counter(
    "seaweedfs_filer_ring_route_total",
    "ring-routed filer operations by outcome",
    labels=("result",),  # ok | failover | error
)

TENANT_INFLIGHT = REGISTRY.gauge(
    "seaweedfs_tenant_inflight",
    "admitted in-flight filer requests per tenant",
    labels=("tenant",),
)
TENANT_ADMIT = REGISTRY.counter(
    "seaweedfs_tenant_admit_total",
    "filer admission decisions per tenant",
    labels=("tenant", "result"),  # ok | slowdown
)
TENANT_USAGE_BYTES = REGISTRY.gauge(
    "seaweedfs_tenant_usage_bytes",
    "logical bytes stored per tenant on this filer shard",
    labels=("tenant",),
)
TENANT_USAGE_OBJECTS = REGISTRY.gauge(
    "seaweedfs_tenant_usage_objects",
    "objects stored per tenant on this filer shard",
    labels=("tenant",),
)

# S3 gateway rejections with proper error XML (503 SlowDown from WFQ
# admission, 403 QuotaExceeded from tenant quotas)
S3_REJECT = REGISTRY.counter(
    "seaweedfs_s3_reject_total",
    "S3 requests rejected by admission control or tenant quotas",
    labels=("reason",),  # slowdown | quota
)


# -- self-healing integrity plane (storage/scrub.py, ISSUE 8) ---------------
# the scrub daemon proactively re-reads sealed volumes (needle CRC against
# the index) and EC shards (recomputed RS parity) under a bytes/s throttle;
# corruption found here or on the read path is quarantined and repaired by
# the master's maintenance repair pass.

SCRUB_BYTES = REGISTRY.counter(
    "seaweedfs_scrub_bytes_total",
    "bytes read and verified by the scrubber, by target kind",
    labels=("kind",),  # volume | ec
)
SCRUB_NEEDLES = REGISTRY.counter(
    "seaweedfs_scrub_needles_total",
    "records verified by the scrubber, by kind and result",
    labels=("kind", "result"),  # volume|ec x ok|corrupt|skipped
)
SCRUB_ERRORS = REGISTRY.counter(
    "seaweedfs_scrub_errors_total",
    "corruption findings by origin",
    labels=("kind",),  # needle | shard | index | vacuum | read_path
)
SCRUB_REPAIRS = REGISTRY.counter(
    "seaweedfs_scrub_repairs_total",
    "self-healing repair attempts by kind and outcome",
    labels=("kind", "result"),  # replica|ec_shard|index x ok|error
)
VOLUME_UNDERREPLICATED = REGISTRY.gauge(
    "seaweedfs_volume_underreplicated",
    "volumes with fewer live replicas than their placement requires",
)


# -- storage lifecycle plane (maintenance/, ISSUE 9) ------------------------
# the master-resident lifecycle controller turns per-collection policies
# into journaled jobs: seal -> ec_encode -> tier -> vacuum -> rebalance ->
# ttl_expire.  `jobs` counts job executions by outcome (ok | error |
# parked | resumed), `transitions` counts completed volume state changes,
# and bytes/seconds attribute the background I/O the shared token bucket
# paces.

LIFECYCLE_JOBS = REGISTRY.counter(
    "seaweedfs_lifecycle_jobs_total",
    "lifecycle job executions by transition and outcome",
    labels=("transition", "result"),  # ok | error | parked | resumed
)
LIFECYCLE_BYTES = REGISTRY.counter(
    "seaweedfs_lifecycle_bytes_total",
    "bytes moved/processed by lifecycle jobs, by transition",
    labels=("transition",),
)
LIFECYCLE_SECONDS = REGISTRY.histogram(
    "seaweedfs_lifecycle_seconds",
    "wall time per lifecycle job, throttle wait included",
    labels=("transition",),
    buckets=(0.01, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
)
LIFECYCLE_TRANSITIONS = REGISTRY.counter(
    "seaweedfs_lifecycle_transitions_total",
    "completed volume lifecycle transitions by result",
    labels=("transition", "result"),  # ok | error
)
LIFECYCLE_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_lifecycle_queue_depth",
    "lifecycle jobs journaled but not yet finished (pending + running)",
)


# -- dead-node mass repair (maintenance/mass_repair.py, ISSUE 11) -----------
# the master-side orchestrator turns a dead node into one planned batch:
# volumes ranked by exposure (fewest surviving shards first), rebuild
# targets spread across the survivors, execution driven through
# cross-volume aggregated partial rpcs.  bytes + seconds give the
# aggregate repair GB/s; deadline slack tracks the configured
# total-repair-time bound.

REPAIR_BATCH_QUEUE_DEPTH = REGISTRY.gauge(
    "seaweedfs_repair_batch_queue_depth",
    "mass-repair volume jobs journaled but not yet finished",
)
REPAIR_BATCH_VOLUMES = REGISTRY.counter(
    "seaweedfs_repair_batch_volumes_total",
    "volumes planned into mass-repair batches by exposure class "
    "(surviving shards above the 10-shard decode floor; lost = below it)",
    labels=("exposure",),  # "0" | "1" | "2" | "3" | "lost"
)
REPAIR_BATCH_JOBS = REGISTRY.counter(
    "seaweedfs_repair_batch_jobs_total",
    "mass-repair volume rebuild executions by outcome",
    labels=("result",),  # ok | error | parked | resumed
)
REPAIR_BATCH_BYTES = REGISTRY.counter(
    "seaweedfs_repair_batch_bytes_total",
    "shard bytes reconstructed by completed mass-repair jobs",
)
REPAIR_BATCH_SECONDS = REGISTRY.histogram(
    "seaweedfs_repair_batch_seconds",
    "wall time per mass-repair wave (one pass over the pending batch)",
    buckets=(0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 1800.0),
)
REPAIR_BATCH_DEADLINE_SLACK = REGISTRY.gauge(
    "seaweedfs_repair_batch_deadline_slack_seconds",
    "configured mass-repair deadline minus projected completion time",
)
# -- cross-cluster geo replication (replication/geo.py, ISSUE 12) ----------
# the geo plane tails the filer's durable metadata event log and ships
# events + object bytes to a peer cluster.  `link` identifies one
# replication direction ("<local_cluster>-><remote filer addr>"); `origin`
# labels the apply side by the SOURCE cluster id.  Conflicts are LWW
# losses on the hybrid logical clock — counted, never silent.

META_LISTENER_ERRORS = REGISTRY.counter(
    "seaweedfs_meta_listener_errors_total",
    "metadata-log listener callback failures; `evicted` counts listeners "
    "unsubscribed after too many consecutive failures",
    labels=("result",),  # error | evicted
)
GEO_EVENTS = REGISTRY.counter(
    "seaweedfs_geo_events_total",
    "metadata events processed by a geo replication link, by outcome",
    labels=("link", "result"),  # shipped | skipped | conflict | dup | error
)
GEO_BYTES = REGISTRY.counter(
    "seaweedfs_geo_bytes_total",
    "object + event bytes shipped over a geo replication link",
    labels=("link",),
)
GEO_LAG = REGISTRY.gauge(
    "seaweedfs_geo_lag_seconds",
    "age of the newest event a geo link has shipped (now - event ts); "
    "the steady-state replication lag of that link",
    labels=("link",),
)
GEO_CONFLICTS = REGISTRY.counter(
    "seaweedfs_geo_conflicts_total",
    "active-active write conflicts resolved by last-writer-wins, by "
    "origin cluster and which side won",
    labels=("origin", "winner"),  # "local": the receiver kept its own
    # newer write (a remote winner applies as a plain "ok", the loser
    # side counts the rejection)
)
GEO_APPLIED = REGISTRY.counter(
    "seaweedfs_geo_applied_total",
    "geo events applied on the receiving cluster, by origin and outcome",
    labels=("origin", "result"),  # ok | dup | conflict
)

GRPC_BYTES = REGISTRY.counter(
    "seaweedfs_grpc_bytes_total",
    "serialized gRPC message bytes through this server, by rpc and "
    "direction — the exact wire payload (sans HTTP/2 framing), which is "
    "what bench A/Bs like --mass-repair measure repair traffic with",
    labels=("type", "op", "direction"),  # rx | tx
)

# -- SLO engine + synthetic canary plane (telemetry/slo.py, canary.py,
# ISSUE 13) -----------------------------------------------------------------
# the master-resident judgment layer: declarative SLO specs evaluated as
# multi-window multi-burn-rate rules over federated counter deltas, fed
# by a black-box canary prober (write/read/delete round trips, EC
# degraded-read, filer/S3 routed PUT/GET, geo sentinel) so "process up
# but serving garbage or slow" pages.

SLO_BURN_RATE = REGISTRY.gauge(
    "seaweedfs_slo_burn_rate",
    "error-budget burn rate per SLO and evaluation window (1.0 = "
    "burning exactly the budget; the page tier fires at its factor in "
    "BOTH windows)",
    labels=("slo", "window"),  # short | long
)
SLO_ALERT_STATE = REGISTRY.gauge(
    "seaweedfs_slo_alert_state",
    "per-SLO alert state (0 ok, 1 pending, 2 firing)",
    labels=("slo", "severity"),  # page | warn
)
SLO_TRANSITIONS = REGISTRY.counter(
    "seaweedfs_slo_alert_transitions_total",
    "alert state-machine transitions by SLO and target state",
    labels=("slo", "to"),  # pending | firing | resolved
)
SLO_EVAL_SECONDS = REGISTRY.histogram(
    "seaweedfs_slo_eval_seconds",
    "wall time per SLO engine evaluation tick (scrape + rule pass)",
)
CANARY_PROBE_TOTAL = REGISTRY.counter(
    "seaweedfs_canary_probe_total",
    "synthetic canary probes by probe kind and outcome; `error` counts "
    "failed or byte-divergent round trips, `skipped` counts probes with "
    "no eligible target",
    labels=("probe", "result"),  # ok | error | skipped
)
CANARY_PROBE_SECONDS = REGISTRY.histogram(
    "seaweedfs_canary_probe_seconds",
    "end-to-end canary probe latency (the black-box SLI the latency "
    "SLOs judge)",
    labels=("probe",),
)
CANARY_STALENESS = REGISTRY.gauge(
    "seaweedfs_canary_staleness_seconds",
    "seconds since a probe kind last fully succeeded (for the geo "
    "sentinel: age of the sentinel payload observed on the remote "
    "cluster)",
    labels=("probe",),
)

# serving plane (ISSUE 18): group-commit fsync barrier + zero-copy reads
# + the selectors event-loop front end.  One fsync acks a whole batch of
# appends, so commits_total << writes_total is the win being measured.
FSYNC_BATCH_COMMITS = REGISTRY.counter(
    "seaweedfs_fsync_batch_commits_total",
    "group-commit flush barriers executed (one fsync pair per commit)",
)
FSYNC_BATCH_WRITES = REGISTRY.counter(
    "seaweedfs_fsync_batch_writes_total",
    "volume mutations acked through a group-commit flush barrier",
)
_FSYNC_BATCH_BUCKETS = tuple(float(2 ** k) for k in range(0, 9))  # 1..256
FSYNC_BATCH_SIZE = REGISTRY.histogram(
    "seaweedfs_fsync_batch_size",
    "mutations committed per flush barrier",
    buckets=_FSYNC_BATCH_BUCKETS,
)
SENDFILE_BYTES = REGISTRY.counter(
    "seaweedfs_sendfile_bytes_total",
    "needle payload bytes served zero-copy via os.sendfile",
)
SENDFILE_FALLBACK = REGISTRY.counter(
    "seaweedfs_sendfile_fallback_total",
    "whole-needle GETs that fell back to the userspace read path",
    labels=("reason",),  # disabled|cache|range|transform|ec|remote|error
)
HTTPD_OPEN_SOCKETS = REGISTRY.gauge(
    "seaweedfs_httpd_open_sockets",
    "connections currently parked on an event-loop HTTP front end",
    labels=("server",),
)
HTTPD_INFLIGHT = REGISTRY.gauge(
    "seaweedfs_httpd_inflight_requests",
    "requests currently executing on an event-loop worker pool",
    labels=("server",),
)
EC_PREADV_BATCHES = REGISTRY.counter(
    "seaweedfs_ec_preadv_batches_total",
    "contiguous EC shard interval runs gathered with one preadv",
)

# flight-recorder plane (ISSUE 20): heavy-hitter attribution sketches
# (telemetry/hotkeys.py) + alert-triggered debug-bundle capture
# (master/flight.py).  hotkey_top_count is deliberately per-key and
# therefore deny-listed from the heartbeat snapshot (see
# SNAPSHOT_DENY_PREFIXES); its cardinality is bounded by the recorder,
# which replaces the child set wholesale on every window rotation.
HOTKEY_EVENTS = REGISTRY.counter(
    "seaweedfs_hotkey_events_total",
    "keys fed to the heavy-hitter sketches, by dimension",
    labels=("dim",),  # needle | bucket | tenant | peer
)
HOTKEY_TRACKED = REGISTRY.gauge(
    "seaweedfs_hotkey_tracked_keys",
    "keys currently tracked by a dimension's space-saving sketch",
    labels=("dim",),
)
HOTKEY_TOP = REGISTRY.gauge(
    "seaweedfs_hotkey_top_count",
    "estimated hits of the hottest keys in the last closed window",
    labels=("dim", "key"),
)
DEBUG_BUNDLES = REGISTRY.counter(
    "seaweedfs_debug_bundles_total",
    "cluster debug bundles captured, by trigger and outcome",
    labels=("trigger", "result"),  # alert|manual ; ok|error
)
DEBUG_BUNDLE_SECONDS = REGISTRY.histogram(
    "seaweedfs_debug_bundle_capture_seconds",
    "wall time to fan out and persist one cluster debug bundle",
)


def serve_metrics(port: int, registry: Registry = REGISTRY,
                  host: str = "0.0.0.0") -> ThreadingHTTPServer:
    """Expose GET /metrics (Prometheus text) and GET /debug/traces (JSON)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            pass

        def do_GET(self):
            import urllib.parse

            path = self.path.split("?")[0]
            if path.startswith("/debug/"):
                from ..telemetry import serve_debug_http

                if serve_debug_http(self, path):
                    return
            if path != "/metrics":
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            query = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            try:
                prefixes = parse_family_prefixes(
                    query.get("family", [""])[0])
            except ValueError as e:
                body = str(e).encode()
                self.send_response(400)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            body = registry.render(prefixes).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = FrameworkHTTPServer((host, port), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
