"""Named fault-injection points for chaos testing the cluster.

Code paths that talk across processes register a named point and call
``inject(name, ctx=...)`` at the top of the risky section.  Points are
inert (one dict lookup) until armed, either

  * via the environment at process start:
      SEAWEEDFS_TPU_FAULTS="volume.http.get=error:3,filer.chunk.fetch=delay:0.5"
    (format: name=mode[:param][:count] — for `error`/`partial` the first
    param is the trigger count, for `delay` it's seconds with an optional
    second count param; no count means "until cleared"), or

  * at runtime through GET /debug/faults on any server's HTTP port:
      /debug/faults                      -> JSON state
      /debug/faults?set=NAME&mode=error&count=3&delay=0.5&match=HOSTPORT
      /debug/faults?clear=NAME           (or clear=all)

``match`` scopes a fault to injection sites whose context string contains
the substring — so a test harness running several volume servers in one
process can kill exactly one of them.

Modes:
  error    raise FaultInjected (an IOError) at the point
  delay    sleep `delay` seconds, then continue normally
  partial  truncate the data passing through the point to half length
           (models a partial write/read); without data, acts like error

Every firing increments seaweedfs_fault_injected_total{point} so chaos
runs can assert the fault actually fired and correlate injected faults
with the retry/breaker metrics they provoke.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from ..stats.metrics import FAULT_COUNTER  # declared centrally for the lint
from . import glog

ENV_VAR = "SEAWEEDFS_TPU_FAULTS"
ENABLE_VAR = "SEAWEEDFS_TPU_FAULTS_ENABLED"
MODES = ("error", "delay", "partial")


def arming_enabled() -> bool:
    """Runtime (HTTP) arming is opt-in: fault points corrupt/deny real
    traffic, so a production server must not accept `?set=` from anyone
    with HTTP reach.  Enabled by the explicit flag, or implicitly when
    the process was already started with faults in its environment (a
    chaos run by definition)."""
    return bool(os.environ.get(ENABLE_VAR) or os.environ.get(ENV_VAR))


class FaultInjected(IOError):
    """An error deliberately injected at a fault point."""


@dataclass
class FaultSpec:
    mode: str
    delay: float = 0.0
    remaining: int = -1  # -1 = until cleared
    match: str = ""  # substring of the injection-site context, "" = all

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "delay": self.delay,
            "remaining": self.remaining,
            "match": self.match,
        }


class FaultRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed: dict[str, FaultSpec] = {}
        self._registered: set[str] = set()

    # -- declaration ------------------------------------------------------

    def register(self, name: str) -> str:
        """Declare a point (import time) so /debug/faults can list it."""
        with self._lock:
            self._registered.add(name)
        return name

    # -- arming -----------------------------------------------------------

    def set(self, name: str, mode: str, delay: float = 0.0,
            count: int = -1, match: str = "") -> None:
        if mode not in MODES:
            raise ValueError(f"unknown fault mode {mode!r} (want {MODES})")
        with self._lock:
            self._registered.add(name)
            self._armed[name] = FaultSpec(mode, delay, count, match)
        glog.warning("fault point armed: %s mode=%s delay=%s count=%d match=%s",
                     name, mode, delay, count, match or "*")

    def clear(self, name: str | None = None) -> None:
        with self._lock:
            if name is None or name == "all":
                self._armed.clear()
            else:
                self._armed.pop(name, None)

    def load_env(self, value: str | None = None) -> None:
        """Parse the SEAWEEDFS_TPU_FAULTS format (see module docstring)."""
        value = os.environ.get(ENV_VAR, "") if value is None else value
        for item in value.split(","):
            item = item.strip()
            if not item or "=" not in item:
                continue
            name, _, spec = item.partition("=")
            parts = spec.split(":")
            mode = parts[0]
            delay, count = 0.0, -1
            try:
                if mode == "delay":
                    if len(parts) > 1:
                        delay = float(parts[1])
                    if len(parts) > 2:
                        count = int(parts[2])
                elif len(parts) > 1:
                    count = int(parts[1])
                self.set(name.strip(), mode, delay=delay, count=count)
            except ValueError as e:
                glog.error("bad %s entry %r: %s", ENV_VAR, item, e)

    # -- injection --------------------------------------------------------

    def inject(self, name: str, ctx: str = "",
               data: bytes | None = None) -> bytes | None:
        """Fire the point if armed; returns (possibly truncated) data."""
        if not self._armed:
            # fast path: hot-path call sites (every needle read/GET) must
            # not pay a lock round trip while no fault is armed; a bare
            # dict truthiness read is atomic under the GIL and arming is
            # always followed by the locked re-check below
            return data
        with self._lock:
            spec = self._armed.get(name)
            if spec is None:
                return data
            if spec.match and spec.match not in ctx:
                return data
            if spec.remaining == 0:
                return data
            if spec.remaining > 0:
                spec.remaining -= 1
        FAULT_COUNTER.labels(name).inc()
        glog.warning("fault injected at %s mode=%s ctx=%s",
                     name, spec.mode, ctx or "-")
        if spec.mode == "delay":
            time.sleep(spec.delay)
            return data
        if spec.mode == "partial" and data is not None:
            return data[: len(data) // 2]
        raise FaultInjected(f"injected fault at {name}")

    # -- introspection ----------------------------------------------------

    def state(self) -> dict:
        with self._lock:
            return {
                "armed": {n: s.to_dict() for n, s in self._armed.items()},
                "registered": sorted(self._registered),
            }


FAULTS = FaultRegistry()
FAULTS.load_env()

# module-level conveniences mirroring the registry API
register = FAULTS.register
inject = FAULTS.inject
set_fault = FAULTS.set
clear_fault = FAULTS.clear
fault_state = FAULTS.state


def handle_debug_request(query: dict) -> dict:
    """Apply a parsed /debug/faults query string; returns the new state.

    query is urllib.parse.parse_qs output.  Raises ValueError on a bad
    mode/number so the HTTP layer can answer 400, PermissionError when
    runtime arming is disabled (answer 403)."""
    if ("set" in query or "clear" in query) and not arming_enabled():
        raise PermissionError(
            f"fault arming disabled; start the process with {ENABLE_VAR}=1")
    if "set" in query:
        name = query["set"][0]
        mode = query.get("mode", ["error"])[0]
        delay = float(query.get("delay", ["0"])[0])
        count = int(query.get("count", ["-1"])[0])
        match = query.get("match", [""])[0]
        FAULTS.set(name, mode, delay=delay, count=count, match=match)
    if "clear" in query:
        FAULTS.clear(query["clear"][0])
    return FAULTS.state()
