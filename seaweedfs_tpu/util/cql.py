"""Framework-native CQL v4 client (Cassandra native protocol) + fake.

No gocql/cassandra-driver equivalent ships in this image, so — like the
RESP/etcd/ES/Mongo clients — the cassandra filer store frames the
native protocol itself: v4 request frames (STARTUP, QUERY with bound
values) and RESULT parsing (Rows / Void).  `FakeCassandraServer`
implements the same frames over an in-memory table and dispatches on
the store's exact prepared-statement shapes, proving the client's
framing without the external service.

Frame layout (native_protocol_v4.spec):
  version u8 (0x04 req / 0x84 resp), flags u8, stream i16, opcode u8,
  length i32, body.
"""

from __future__ import annotations

import socket
import struct
import threading

OP_STARTUP = 0x01
OP_READY = 0x02
OP_ERROR = 0x00
OP_QUERY = 0x07
OP_RESULT = 0x08

_CONSISTENCY_LOCAL_QUORUM = 0x0006
_FLAG_VALUES = 0x01

RESULT_VOID = 0x0001
RESULT_ROWS = 0x0002


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">i", len(b)) + b


def _string_map(m: dict[str, str]) -> bytes:
    out = struct.pack(">H", len(m))
    for k, v in m.items():
        out += _string(k) + _string(v)
    return out


def _bytes_value(v: bytes | None) -> bytes:
    if v is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(v)) + v


def _read_exact(sock: socket.socket, n: int) -> bytes:
    from .netio import read_exact

    return read_exact(sock, n, "cql")


def _frame(opcode: int, body: bytes, stream: int = 0,
           response: bool = False) -> bytes:
    version = 0x84 if response else 0x04
    return struct.pack(">BBhBi", version, 0, stream, opcode,
                       len(body)) + body


def _read_frame(sock: socket.socket) -> tuple[int, int, bytes]:
    hdr = _read_exact(sock, 9)
    _ver, _flags, stream, opcode, length = struct.unpack(">BBhBi", hdr)
    return stream, opcode, _read_exact(sock, length) if length else b""


class CqlClient:
    """One QUERY round trip per call; reconnects a stale pooled socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 9042,
                 timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.sendall(_frame(OP_STARTUP,
                             _string_map({"CQL_VERSION": "3.0.0"})))
            _stream, opcode, body = _read_frame(s)
            if opcode != OP_READY:
                s.close()
                raise IOError(f"cql startup failed: opcode {opcode}")
            self._sock = s
        return self._sock

    PAGE_SIZE = 5000  # result paging keeps any single frame bounded

    def query(self, cql: str,
              values: list[bytes | None] | None = None,
              max_rows: int | None = None) -> list[list[bytes | None]]:
        """Execute one statement with blob-typed bound values; returns
        rows of cell blobs (RESULT Rows) or [] (Void).  Follows result
        paging (has_more_pages + paging_state) so cluster-wide scans
        arrive in bounded frames; `max_rows` stops requesting pages once
        the caller has enough — a bounded listing must not transfer a
        million-row partition."""
        rows: list[list[bytes | None]] = []
        paging_state: bytes | None = None
        while True:
            flags = 0x04  # page_size always present
            tail = struct.pack(">i", self.PAGE_SIZE)
            if values:
                flags |= _FLAG_VALUES
            if paging_state is not None:
                flags |= 0x08
                tail += _bytes_value(paging_state)
            body = _long_string(cql)
            body += struct.pack(">H", _CONSISTENCY_LOCAL_QUORUM)
            body += struct.pack(">B", flags)
            if values:
                body += struct.pack(">H", len(values))
                for v in values:
                    body += _bytes_value(v)
            body += tail
            with self._lock:
                try:
                    sock = self._conn()
                    sock.sendall(_frame(OP_QUERY, body))
                    _stream, opcode, payload = _read_frame(sock)
                except (OSError, ConnectionError):
                    self.close()
                    sock = self._conn()
                    sock.sendall(_frame(OP_QUERY, body))
                    _stream, opcode, payload = _read_frame(sock)
            if opcode == OP_ERROR:
                code = struct.unpack_from(">i", payload, 0)[0]
                n = struct.unpack_from(">H", payload, 4)[0]
                msg = payload[6:6 + n].decode()
                raise IOError(f"cql error 0x{code:04x}: {msg}")
            if opcode != OP_RESULT:
                raise IOError(f"unexpected cql opcode {opcode}")
            kind = struct.unpack_from(">i", payload, 0)[0]
            if kind != RESULT_ROWS:
                return rows
            page, paging_state = self._parse_rows(payload)
            rows.extend(page)
            if paging_state is None or (
                    max_rows is not None and len(rows) >= max_rows):
                return rows

    @staticmethod
    def _parse_rows(
        payload: bytes,
    ) -> tuple[list[list[bytes | None]], bytes | None]:
        at = 4
        flags, col_count = struct.unpack_from(">ii", payload, at)
        at += 8
        paging_state = None
        if flags & 0x0002:  # has_more_pages: paging state
            n = struct.unpack_from(">i", payload, at)[0]
            at += 4
            if n > 0:
                paging_state = payload[at:at + n]
                at += n
        if not flags & 0x0001:  # no global_tables_spec
            pass
        else:
            for _ in range(2):  # keyspace + table
                n = struct.unpack_from(">H", payload, at)[0]
                at += 2 + n
        for _ in range(col_count):  # column specs
            if not flags & 0x0001:
                for _ in range(2):
                    n = struct.unpack_from(">H", payload, at)[0]
                    at += 2 + n
            n = struct.unpack_from(">H", payload, at)[0]  # name
            at += 2 + n
            opt = struct.unpack_from(">H", payload, at)[0]  # type id
            at += 2
            if opt in (0x0000, 0x0020, 0x0021, 0x0022, 0x0030):
                raise IOError("complex CQL column types unsupported")
        row_count = struct.unpack_from(">i", payload, at)[0]
        at += 4
        rows = []
        for _ in range(row_count):
            row: list[bytes | None] = []
            for _ in range(col_count):
                n = struct.unpack_from(">i", payload, at)[0]
                at += 4
                if n < 0:
                    row.append(None)
                else:
                    row.append(payload[at:at + n])
                    at += n
            rows.append(row)
        return rows, paging_state

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# ---------------------------------------------------------------------------
# Fake server: the filemeta statement shapes over an in-memory table
# ---------------------------------------------------------------------------


class FakeCassandraServer:
    """CQL v4 framing + the cassandra store's statements.

    Table model: {(directory, name) -> meta blob}, sorted by name within
    a directory (the clustering order a (directory, name) primary key
    gives the real store).
    """

    def __init__(self, port: int = 0):
        self.port = port
        self._rows: dict[tuple[bytes, bytes], bytes] = {}
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self._stop = threading.Event()

    def _execute(self, cql: str, vals: list[bytes | None]) -> list:
        q = " ".join(cql.split()).lower()
        with self._lock:
            if q.startswith("insert into filemeta"):
                d, n, m = vals[0] or b"", vals[1] or b"", vals[2] or b""
                self._rows[(d, n)] = m
                return []
            if q.startswith("select distinct directory from filemeta"):
                return [[d] for d in sorted({k[0] for k in self._rows})]
            if q.startswith("select meta from filemeta where directory = ? and name = ?"):
                m = self._rows.get((vals[0] or b"", vals[1] or b""))
                return [] if m is None else [[m]]
            if q.startswith("select name, meta from filemeta where directory = ? and name >= ?"):
                return self._list(vals[0] or b"", vals[1] or b"", ge=True)
            if q.startswith("select name, meta from filemeta where directory = ? and name > ?"):
                return self._list(vals[0] or b"", vals[1] or b"", ge=False)
            if q.startswith("select name, meta from filemeta where directory = ?"):
                return self._list(vals[0] or b"", b"", ge=True)
            if q.startswith("delete from filemeta where directory = ? and name = ?"):
                self._rows.pop((vals[0] or b"", vals[1] or b""), None)
                return []
            if q.startswith("delete from filemeta where directory = ?"):
                d = vals[0] or b""
                for k in [k for k in self._rows if k[0] == d]:
                    del self._rows[k]
                return []
            raise ValueError(f"fake cassandra: unsupported statement {cql!r}")

    def _list(self, d: bytes, start: bytes, ge: bool) -> list:
        out = []
        for (rd, rn), m in sorted(self._rows.items()):
            if rd != d:
                continue
            if start and (rn < start if ge else rn <= start):
                continue
            out.append([rn, m])
        return out

    def _rows_result(self, rows: list) -> bytes:
        cols = 2 if rows and len(rows[0]) == 2 else 1
        body = struct.pack(">iii", RESULT_ROWS, 0x0001, cols)
        body += _string("ks") + _string("filemeta")
        for i in range(cols):
            body += _string(f"c{i}") + struct.pack(">H", 0x0003)  # blob
        body += struct.pack(">i", len(rows))
        for row in rows:
            for cell in row:
                body += _bytes_value(cell)
        return body

    def _client_loop(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    stream, opcode, payload = _read_frame(conn)
                except (ConnectionError, OSError, struct.error):
                    return
                if opcode == OP_STARTUP:
                    conn.sendall(_frame(OP_READY, b"", stream, True))
                    continue
                if opcode != OP_QUERY:
                    conn.sendall(_frame(
                        OP_ERROR,
                        struct.pack(">i", 0x000A) + _string("bad opcode"),
                        stream, True))
                    continue
                n = struct.unpack_from(">i", payload, 0)[0]
                cql = payload[4:4 + n].decode()
                at = 4 + n + 2  # consistency
                flags = payload[at]
                at += 1
                vals: list[bytes | None] = []
                if flags & _FLAG_VALUES:
                    count = struct.unpack_from(">H", payload, at)[0]
                    at += 2
                    for _ in range(count):
                        ln = struct.unpack_from(">i", payload, at)[0]
                        at += 4
                        if ln < 0:
                            vals.append(None)
                        else:
                            vals.append(payload[at:at + ln])
                            at += ln
                try:
                    rows = self._execute(cql, vals)
                except ValueError as e:
                    conn.sendall(_frame(
                        OP_ERROR,
                        struct.pack(">i", 0x2200) + _string(str(e)),
                        stream, True))
                    continue
                if rows:
                    body = self._rows_result(rows)
                else:
                    # Void for writes; empty Rows for selects
                    if cql.lstrip().lower().startswith("select"):
                        body = self._rows_result([])
                    else:
                        body = struct.pack(">i", RESULT_VOID)
                conn.sendall(_frame(OP_RESULT, body, stream, True))
        finally:
            conn.close()

    def start(self) -> None:
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", self.port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except OSError:
                    return
                threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
