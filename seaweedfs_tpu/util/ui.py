"""Minimal per-server HTML status pages.

Reference: weed/server/*_ui/ — each process serves /ui/index.html with
its live status.  One shared renderer keeps every server's page
consistent; values come from the same dicts the JSON status endpoints
return.
"""

from __future__ import annotations

import html

_STYLE = """
body{font-family:system-ui,sans-serif;margin:2em;color:#222}
h1{font-size:1.3em} h2{font-size:1.05em;margin-top:1.4em}
table{border-collapse:collapse;margin-top:.4em}
td,th{border:1px solid #ccc;padding:.25em .6em;text-align:left;
font-size:.9em} th{background:#f2f2f2}
.k{color:#666}
"""


def _render_value(v) -> str:
    if isinstance(v, dict):
        rows = "".join(
            f"<tr><td class=k>{html.escape(str(k))}</td>"
            f"<td>{_render_value(x)}</td></tr>" for k, x in v.items())
        return f"<table>{rows}</table>"
    if isinstance(v, list):
        if v and isinstance(v[0], dict):
            keys = list(v[0].keys())
            head = "".join(f"<th>{html.escape(str(k))}</th>" for k in keys)
            rows = "".join(
                "<tr>" + "".join(
                    f"<td>{_render_value(row.get(k, ''))}</td>"
                    for k in keys) + "</tr>"
                for row in v)
            return f"<table><tr>{head}</tr>{rows}</table>"
        return html.escape(", ".join(str(x) for x in v))
    return html.escape(str(v))


def render_status_page(title: str, sections: dict[str, object]) -> bytes:
    parts = [f"<!doctype html><html><head><meta charset=utf-8>"
             f"<title>{html.escape(title)}</title>"
             f"<style>{_STYLE}</style></head><body>"
             f"<h1>{html.escape(title)}</h1>"]
    for name, data in sections.items():
        parts.append(f"<h2>{html.escape(name)}</h2>")
        parts.append(_render_value(data))
    parts.append("</body></html>")
    return "".join(parts).encode()
