"""Thread pools that report their own saturation.

PRs 3-4 added executors all over the data plane (replica fan-out, EC
degraded-read fetches, rebuild source reads, filer chunk fan-out) with no
visibility: a stalled stage only shows up as a throughput drop somewhere
downstream.  `MeteredThreadPoolExecutor` is a drop-in
concurrent.futures.ThreadPoolExecutor that keeps three gauges per pool —

    seaweedfs_executor_queue_depth{executor}    submitted, not started
    seaweedfs_executor_active_workers{executor} running right now
    seaweedfs_executor_max_workers{executor}    capacity

so "is the pool the bottleneck" is `active == max and queue_depth > 0`
in PromQL instead of a guess.  The accounting wraps the submitted
callable (one int inc/dec either side of the call); overhead is two
lock-protected float adds per task, noise against any task that does
I/O.
"""

from __future__ import annotations

import concurrent.futures

from ..stats.metrics import (
    EXECUTOR_ACTIVE,
    EXECUTOR_MAX,
    EXECUTOR_QUEUE_DEPTH,
)


class MeteredThreadPoolExecutor(concurrent.futures.ThreadPoolExecutor):
    """ThreadPoolExecutor whose queue depth / active workers are gauges.

    `name` is the `executor` label value; instances sharing a name share
    the gauge children (intended for per-call pools like the rebuild's
    source readers, where the family tracks the stage, not the object).
    """

    def __init__(self, max_workers: int, name: str, **kwargs):
        super().__init__(max_workers=max_workers, **kwargs)
        self.name = name
        self._g_queue = EXECUTOR_QUEUE_DEPTH.labels(name)
        self._g_active = EXECUTOR_ACTIVE.labels(name)
        EXECUTOR_MAX.labels(name).set(max_workers)

    def submit(self, fn, /, *args, **kwargs):
        g_queue, g_active = self._g_queue, self._g_active

        def run(*a, **kw):
            g_queue.dec()
            g_active.inc()
            try:
                return fn(*a, **kw)
            finally:
                g_active.dec()

        g_queue.inc()
        try:
            fut = super().submit(run, *args, **kwargs)
        except BaseException:
            g_queue.dec()  # RuntimeError on a shut-down pool, etc.
            raise
        # a CANCELLED future never runs its callable, so run()'s dec never
        # fires — Executor.map cancels pending futures when the consumer
        # raises mid-iteration, which would leak queue_depth permanently
        fut.add_done_callback(
            lambda f: g_queue.dec() if f.cancelled() else None)
        return fut
