"""glog-style leveled logging: I/W/E lines with V-levels and rotation.

Reference: weed/glog/glog.go:71 — `glog.V(n)` gates verbose logs on the
process-wide verbosity; Info/Warning/Error always emit.  Format:
`I0729 10:32:01.123456 module.py:42] message`.

Usage:
    from seaweedfs_tpu.util import glog
    glog.info("volume %d mounted", vid)
    if glog.V(2): glog.info("per-read detail ...")
    glog.set_verbosity(3)
"""

from __future__ import annotations

import io
import os
import sys
import threading
import time

_LEVEL_CHAR = {"info": "I", "warning": "W", "error": "E", "fatal": "F"}

_state = threading.local()
_lock = threading.Lock()
_verbosity = int(os.environ.get("SEAWEEDFS_TPU_V", "0"))
_sink = sys.stderr
_max_bytes = 0  # 0 = no rotation
_log_path: str | None = None
_written = 0
# optional per-line context (e.g. the active trace id) resolved at emit
# time from a thread-local; installed by telemetry.trace at import
_context_fn = None


def set_context_provider(fn) -> None:
    """Install a zero-arg callable whose non-None return value is stamped
    into every log line as `trace=<value>` (the log<->trace join key)."""
    global _context_fn
    _context_fn = fn


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = int(v)


def V(level: int) -> bool:
    """True when verbose logs at this level should emit."""
    return _verbosity >= level


def set_output(path_or_file, max_bytes: int = 64 << 20) -> None:
    """Log to a file (rotating at max_bytes, like glog MaxSize) or stream."""
    global _sink, _log_path, _max_bytes, _written
    with _lock:
        if isinstance(path_or_file, str):
            _log_path = path_or_file
            _max_bytes = max_bytes
            _sink = open(path_or_file, "a", buffering=1)
            _written = _sink.tell()
        else:
            _log_path = None
            _max_bytes = 0
            _sink = path_or_file


def _emit(level: str, fmt: str, *args) -> None:
    global _sink, _written
    msg = (fmt % args) if args else fmt
    frame = sys._getframe(2)
    where = f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"
    now = time.time()
    stamp = time.strftime("%m%d %H:%M:%S", time.localtime(now))
    micros = int((now % 1) * 1e6)
    ctx = ""
    if _context_fn is not None:
        try:
            val = _context_fn()
        except Exception:
            val = None
        if val:
            ctx = f" trace={val}"
    line = f"{_LEVEL_CHAR[level]}{stamp}.{micros:06d} {where}{ctx}] {msg}\n"
    with _lock:
        try:
            _sink.write(line)
            _written += len(line)
            if _max_bytes and _log_path and _written >= _max_bytes:
                # rotate atomically from the logger's view: whatever
                # happens to os.replace, _sink ends up an OPEN handle on
                # _log_path.  (Previously a failed replace left _sink
                # closed and every later log was silently dropped.)
                _sink.close()
                try:
                    os.replace(_log_path, _log_path + ".1")
                finally:
                    _sink = open(_log_path, "a", buffering=1)
                    _written = _sink.tell()
        except (OSError, ValueError, io.UnsupportedOperation):
            pass


def info(fmt: str, *args) -> None:
    _emit("info", fmt, *args)


def warning(fmt: str, *args) -> None:
    _emit("warning", fmt, *args)


def error(fmt: str, *args) -> None:
    _emit("error", fmt, *args)


def flush() -> None:
    """Flush the active sink; never raises (a dead sink is not fatal)."""
    with _lock:
        try:
            _sink.flush()
        except (OSError, ValueError, io.UnsupportedOperation):
            pass


def fatal(fmt: str, *args) -> None:
    _emit("fatal", fmt, *args)
    # the process is about to exit: make sure the F line hits the disk
    # before SystemExit unwinds (a block-buffered file sink would
    # otherwise lose the one line that explains the death)
    flush()
    raise SystemExit(1)
