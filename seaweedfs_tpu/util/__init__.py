"""Cross-cutting utilities: logging, compression, cipher, caching,
throttling, config.

Reference surface: weed/glog, weed/util.
"""
