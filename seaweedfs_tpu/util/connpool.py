"""Keep-alive HTTP connection pool: the one way the framework talks to
itself over HTTP.

Reference analogue: weed/util/http/client.go — the reference shares one
net/http.Transport (keep-alive, per-host idle pools) across every
internal hop, so a small-file write costs zero TCP handshakes after
warm-up.  The seed paid a fresh connect per hop via
urllib.request.urlopen; at ~3k reqs/s the SYN/ACK round trips and slow
starts dominated the serving plane (see ISSUE 3 / BENCH_r05).

Design:

  * bounded per-peer idle pools ((host, port) keyed); excess or
    idle-expired sockets are closed and counted as evictions;
  * TCP_NODELAY on every dial — internal requests are small and
    latency-bound, Nagle only adds delay;
  * stale-connection retry: a keep-alive socket the peer closed while
    pooled fails its next use with a connection-drop error *before any
    byte of the response arrives*; that request is replayed ONCE on a
    fresh dial.  Timeouts and errors on fresh connections are NOT
    retried here — retry policy belongs to util/failsafe, which wraps
    these calls at every call site;
  * `urllib.error.HTTPError` raised for >= 400 responses and GET/HEAD
    redirects followed, so failsafe.classify and existing callers see
    exactly the exception surface urlopen gave them.

Metrics: seaweedfs_connpool_{reuse,dial,evict}_total.
"""

from __future__ import annotations

import http.client
import io
import socket
import threading
import time
import urllib.error
import urllib.parse

from ..stats.metrics import (
    CONNPOOL_DIAL,
    CONNPOOL_EVICT,
    CONNPOOL_IDLE,
    CONNPOOL_IN_USE,
    CONNPOOL_REUSE,
)

# label-less children resolved once — Metric.labels() takes the metric
# lock and these fire on every internal request
_REUSE = CONNPOOL_REUSE.labels()
_DIAL = CONNPOOL_DIAL.labels()
_EVICT = CONNPOOL_EVICT.labels()

# per-peer saturation gauges, (in_use, idle) pairs cached by (host, port)
# key so the hot path pays a dict hit, not the metric lock.  One atomic
# assignment of the whole pair: two threads first-touching a peer may
# both build it, but labels() dedupes children, and neither can observe
# a half-populated entry
_peer_gauge_pairs: dict = {}


def _peer_gauges(key: tuple):
    pair = _peer_gauge_pairs.get(key)
    if pair is None:
        peer = f"{key[0]}:{key[1]}"
        pair = (CONNPOOL_IN_USE.labels(peer), CONNPOOL_IDLE.labels(peer))
        _peer_gauge_pairs[key] = pair
    return pair

DEFAULT_TIMEOUT = 30.0
MAX_IDLE_PER_HOST = 8
IDLE_TTL_S = 60.0
MAX_REDIRECTS = 5

# errors that mean "the pooled socket died while idle" when they hit a
# REUSED connection before any response byte: safe to replay once on a
# fresh dial, even for POSTs (the peer provably processed nothing)
_STALE_ERRORS = (
    http.client.BadStatusLine,  # includes RemoteDisconnected
    ConnectionResetError,
    ConnectionAbortedError,
    BrokenPipeError,
)


class PooledResponse:
    """File-like response (status/headers/read/close) that returns its
    connection to the pool once the body is fully drained."""

    def __init__(self, pool: "ConnectionPool", key: tuple,
                 conn: http.client.HTTPConnection,
                 resp: http.client.HTTPResponse, url: str):
        self._pool = pool
        self._key = key
        self._conn = conn
        self._resp = resp
        self._released = False
        self.url = url
        self.status = resp.status
        self.reason = resp.reason
        self.headers = resp.headers

    # mirror the urlopen response surface callers already use
    def read(self, amt: int | None = None) -> bytes:
        data = self._resp.read() if amt is None else self._resp.read(amt)
        if self._resp.isclosed():
            self._release(reusable=True)
        return data

    def getheader(self, name: str, default=None):
        return self._resp.getheader(name, default)

    def geturl(self) -> str:
        return self.url

    def _release(self, reusable: bool) -> None:
        if self._released:
            return
        self._released = True
        _peer_gauges(self._key)[0].dec()  # checkout ends either way
        if reusable and not self._resp.will_close:
            self._pool._put(self._key, self._conn)
        else:
            self._conn.close()

    def close(self) -> None:
        if self._released:
            return
        if self._resp.isclosed():
            self._release(reusable=True)
        else:
            # undrained body would desync the keep-alive framing: drop
            self._release(reusable=False)

    def __enter__(self) -> "PooledResponse":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class ConnectionPool:
    def __init__(self, max_idle_per_host: int = MAX_IDLE_PER_HOST,
                 idle_ttl: float = IDLE_TTL_S):
        self.max_idle_per_host = max_idle_per_host
        self.idle_ttl = idle_ttl
        self._lock = threading.Lock()
        # (host, port) -> [(conn, idle_since), ...] newest last
        self._idle: dict[tuple, list] = {}

    # -- socket lifecycle -------------------------------------------------

    def _get(self, key: tuple, timeout: float | None):
        """-> (conn, reused).  Pops the freshest idle socket, evicting
        any that sat past the idle TTL."""
        now = time.monotonic()
        _, g_idle = _peer_gauges(key)
        with self._lock:
            bucket = self._idle.get(key)
            while bucket:
                conn, since = bucket.pop()
                if now - since > self.idle_ttl:
                    _EVICT.inc()
                    conn.close()
                    continue
                g_idle.set(len(bucket))
                conn.timeout = timeout
                if conn.sock is not None:
                    conn.sock.settimeout(timeout)
                _REUSE.inc()
                return conn, True
            g_idle.set(len(bucket or ()))
        return self._dial(key, timeout), False

    def _dial(self, key: tuple, timeout: float | None):
        host, port = key
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        conn.connect()
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _DIAL.inc()
        return conn

    def _put(self, key: tuple, conn: http.client.HTTPConnection) -> None:
        if conn.sock is None:
            return
        _, g_idle = _peer_gauges(key)
        with self._lock:
            bucket = self._idle.setdefault(key, [])
            bucket.append((conn, time.monotonic()))
            while len(bucket) > self.max_idle_per_host:
                old, _ = bucket.pop(0)
                _EVICT.inc()
                old.close()
            g_idle.set(len(bucket))

    def close_all(self) -> None:
        with self._lock:
            for key, bucket in self._idle.items():
                for conn, _ in bucket:
                    conn.close()
                _peer_gauges(key)[1].set(0)
            self._idle.clear()

    def idle_count(self, host: str, port: int) -> int:
        with self._lock:
            return len(self._idle.get((host, port), ()))

    # -- requests ---------------------------------------------------------

    def request(self, method: str, url: str, body=None,
                headers: dict | None = None,
                timeout: float | None = DEFAULT_TIMEOUT) -> PooledResponse:
        """One internal HTTP request on a pooled connection.

        Raises urllib.error.HTTPError for >= 400 (body attached, the
        connection still returns to the pool), follows GET/HEAD
        redirects, and surfaces connect/transport errors unchanged so
        failsafe.classify and the per-peer breakers see them.
        """
        for _hop in range(MAX_REDIRECTS + 1):
            resp = self._request_once(method, url, body, headers, timeout)
            if (resp.status in (301, 302, 303, 307, 308)
                    and method in ("GET", "HEAD")):
                location = resp.getheader("Location")
                if not location:
                    return resp
                resp.read()  # drain so the connection can be reused
                resp.close()
                url = urllib.parse.urljoin(url, location)
                continue
            if resp.status >= 400:
                payload = resp.read()
                resp.close()
                raise urllib.error.HTTPError(
                    url, resp.status, resp.reason, resp.headers,
                    io.BytesIO(payload))
            return resp
        raise urllib.error.HTTPError(
            url, 310, "too many redirects", {}, io.BytesIO())

    def _request_once(self, method, url, body, headers,
                      timeout) -> PooledResponse:
        parts = urllib.parse.urlsplit(url)
        if parts.scheme not in ("", "http"):
            raise ValueError(f"connpool handles plain http only: {url}")
        key = (parts.hostname or "127.0.0.1", parts.port or 80)
        target = parts.path or "/"
        if parts.query:
            target += "?" + parts.query
        # a non-seekable streaming body can't be replayed on a stale
        # socket — send it on a fresh dial instead of risking the replay
        streaming = body is not None and not isinstance(
            body, (bytes, bytearray, memoryview))
        can_replay = not streaming or (
            getattr(body, "seekable", lambda: False)())
        conn, reused = (self._get(key, timeout) if can_replay
                        else (self._dial(key, timeout), False))
        g_in_use = _peer_gauges(key)[0]
        g_in_use.inc()  # checked out until PooledResponse._release
        for attempt in (0, 1):
            try:
                conn.request(method, target, body=body,
                             headers=dict(headers or {}))
                resp = conn.getresponse()
                return PooledResponse(self, key, conn, resp, url)
            except _STALE_ERRORS:
                conn.close()
                if not reused or attempt:
                    g_in_use.dec()
                    raise
                # the peer closed the socket while it sat in the pool:
                # replay exactly once on a fresh dial.  The re-dial (or
                # seek) itself failing must also end the checkout, or the
                # in_use gauge inflates forever on peer outages
                _EVICT.inc()
                try:
                    if streaming:
                        body.seek(0)
                    conn = self._dial(key, timeout)
                except BaseException:
                    g_in_use.dec()
                    raise
                reused = False
            except BaseException:
                conn.close()
                g_in_use.dec()
                raise
        raise AssertionError("unreachable")  # pragma: no cover


# process-wide pool shared by every internal caller
POOL = ConnectionPool()


def request(method: str, url: str, body=None, headers: dict | None = None,
            timeout: float | None = DEFAULT_TIMEOUT) -> PooledResponse:
    return POOL.request(method, url, body=body, headers=headers,
                        timeout=timeout)


def close_all() -> None:
    POOL.close_all()
