"""Tiered chunk cache: bounded in-memory LRU + on-disk spill tier.

Reference: weed/util/chunk_cache/chunk_cache.go:25 (TieredChunkCache) —
small chunks live in a memory LRU, larger ones go to disk-backed cache
volumes, each tier bounded and keyed by fid.  Readers (mount, filer HTTP,
S3 gateway) consult the cache before any volume-server round trip.

Own design notes: the reference spills to its own needle files with three
size classes; here the disk tier is a flat sharded directory with
LRU-by-access eviction driven from an in-memory index — same contract
(bounded bytes, fid-keyed, survives cache-object lifetime but not designed
to persist across restarts), much less machinery.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

from ..stats.metrics import (
    CHUNK_CACHE_COUNTER,
    EC_INTERVAL_CACHE,
    NEEDLE_CACHE_EVICT,
    NEEDLE_CACHE_HIT,
    NEEDLE_CACHE_MISS,
)

# resolve the label-less children once: Metric.labels() takes the metric
# lock, and these fire on every needle read
_NC_HIT = NEEDLE_CACHE_HIT.labels()
_NC_MISS = NEEDLE_CACHE_MISS.labels()
_NC_EVICT = NEEDLE_CACHE_EVICT.labels()
_IC_HIT = EC_INTERVAL_CACHE.labels("hit")
_IC_MISS = EC_INTERVAL_CACHE.labels("miss")
_IC_EVICT = EC_INTERVAL_CACHE.labels("evict")


class MemoryChunkCache:
    """Byte-bounded LRU of fid -> chunk bytes."""

    def __init__(self, limit_bytes: int = 64 << 20,
                 max_entry_bytes: int = 4 << 20):
        self.limit = limit_bytes
        self.max_entry = max_entry_bytes
        self._lock = threading.Lock()
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self._bytes = 0

    def get(self, fid: str) -> bytes | None:
        with self._lock:
            data = self._data.get(fid)
            if data is not None:
                self._data.move_to_end(fid)
            return data

    def set(self, fid: str, data: bytes) -> bool:
        if len(data) > self.max_entry or len(data) > self.limit:
            return False
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._bytes -= len(old)
            self._data[fid] = data
            self._bytes += len(data)
            while self._bytes > self.limit and self._data:
                _, evicted = self._data.popitem(last=False)
                self._bytes -= len(evicted)
            return True

    def __len__(self) -> int:
        return len(self._data)


class NeedleCache:
    """Bytes-bounded LRU of hot needles on the volume-server read path.

    Keyed (volume_id, needle_id); values are whole parsed Needle objects
    (treated as immutable by every reader), so a hit skips the needle-map
    lookup, the disk read AND the header/CRC parse.  Writers invalidate
    per needle on every append/delete; vacuum and volume removal drop the
    whole volume's entries.  Same LRU-by-bytes discipline as
    MemoryChunkCache above, with its own metric family
    seaweedfs_needle_cache_{hit,miss,evict}_total.
    """

    def __init__(self, limit_bytes: int = 32 << 20,
                 max_entry_bytes: int = 1 << 20):
        self.limit = limit_bytes
        self.max_entry = max_entry_bytes
        self._lock = threading.Lock()
        self._data: OrderedDict[tuple[int, int], tuple] = OrderedDict()
        self._bytes = 0

    @staticmethod
    def _size_of(needle) -> int:
        # payload dominates; 64B covers header fields + dict slot
        return len(needle.data) + 64

    def get(self, vid: int, needle_id: int):
        with self._lock:
            entry = self._data.get((vid, needle_id))
            if entry is None:
                _NC_MISS.inc()
                return None
            self._data.move_to_end((vid, needle_id))
            _NC_HIT.inc()
            return entry[0]

    def put(self, vid: int, needle_id: int, needle) -> bool:
        size = self._size_of(needle)
        if size > self.max_entry or size > self.limit:
            return False
        with self._lock:
            old = self._data.pop((vid, needle_id), None)
            if old is not None:
                self._bytes -= old[1]
            self._data[(vid, needle_id)] = (needle, size)
            self._bytes += size
            while self._bytes > self.limit and self._data:
                _, (_n, sz) = self._data.popitem(last=False)
                self._bytes -= sz
                _NC_EVICT.inc()
            return True

    def invalidate(self, vid: int, needle_id: int) -> None:
        with self._lock:
            old = self._data.pop((vid, needle_id), None)
            if old is not None:
                self._bytes -= old[1]

    def drop_volume(self, vid: int) -> None:
        """Remove every cached needle of one volume (vacuum commit,
        volume delete/unmount — offsets and liveness may have changed
        wholesale)."""
        with self._lock:
            doomed = [k for k in self._data if k[0] == vid]
            for k in doomed:
                self._bytes -= self._data.pop(k)[1]

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

    def __len__(self) -> int:
        return len(self._data)


class IntervalCache:
    """Bytes-bounded LRU of RECONSTRUCTED EC shard intervals on the
    degraded-read path.

    Keyed (shard_id, offset, length); every entry carries the volume's
    invalidation token — (mount_seq, delete_seq) — captured BEFORE the
    gather that produced it.  A get with a newer token drops the entry
    (shard mount/unmount re-copies files wholesale; a delete bumps
    delete_seq), the same compare-before-publish discipline as the
    needle cache above.  Metric family
    seaweedfs_ec_interval_cache_total{result}.
    """

    def __init__(self, limit_bytes: int = 8 << 20,
                 max_entry_bytes: int = 1 << 20):
        self.limit = limit_bytes
        self.max_entry = max_entry_bytes
        self._lock = threading.Lock()
        self._data: OrderedDict[tuple, tuple[bytes, tuple]] = OrderedDict()
        self._bytes = 0

    def get(self, key: tuple, token: tuple) -> bytes | None:
        with self._lock:
            entry = self._data.get(key)
            if entry is None:
                _IC_MISS.inc()
                return None
            data, entry_token = entry
            if entry_token != token:
                # captured under an older shard layout / delete state
                self._bytes -= len(data)
                del self._data[key]
                _IC_MISS.inc()
                return None
            self._data.move_to_end(key)
            _IC_HIT.inc()
            return data

    def put(self, key: tuple, data: bytes, token: tuple) -> bool:
        if len(data) > self.max_entry or len(data) > self.limit:
            return False
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= len(old[0])
            self._data[key] = (data, token)
            self._bytes += len(data)
            while self._bytes > self.limit and self._data:
                _, (evicted, _t) = self._data.popitem(last=False)
                self._bytes -= len(evicted)
                _IC_EVICT.inc()
            return True

    def clear(self) -> None:
        with self._lock:
            self._data.clear()
            self._bytes = 0

    def __len__(self) -> int:
        return len(self._data)


class DiskChunkCache:
    """Disk spill tier: one file per cached chunk under a sharded dir."""

    def __init__(self, directory: str, limit_bytes: int = 1 << 30):
        self.directory = directory
        self.limit = limit_bytes
        self._lock = threading.Lock()
        self._index: OrderedDict[str, int] = OrderedDict()  # fid -> size
        self._bytes = 0
        os.makedirs(directory, exist_ok=True)

    def _path(self, fid: str) -> str:
        h = hashlib.sha1(fid.encode()).hexdigest()
        return os.path.join(self.directory, h[:2], h[2:])

    def get(self, fid: str) -> bytes | None:
        with self._lock:
            if fid not in self._index:
                return None
            self._index.move_to_end(fid)
        try:
            with open(self._path(fid), "rb") as f:
                return f.read()
        except OSError:
            with self._lock:
                size = self._index.pop(fid, 0)
                self._bytes -= size
            return None

    def set(self, fid: str, data: bytes) -> bool:
        if len(data) > self.limit:
            return False
        path = self._path(fid)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError:
            return False
        with self._lock:
            old = self._index.pop(fid, None)
            if old is not None:
                self._bytes -= old
            self._index[fid] = len(data)
            self._bytes += len(data)
            while self._bytes > self.limit and self._index:
                evict_fid, size = self._index.popitem(last=False)
                self._bytes -= size
                try:
                    os.remove(self._path(evict_fid))
                except OSError:
                    pass
        return True


class TieredChunkCache:
    """Memory first, then disk; sets go to the tier that fits.

    Chunks at or under ``mem_max_entry`` live in memory; bigger ones go to
    disk (when a disk dir was given).  A disk hit is promoted to memory if
    it fits, mirroring the reference's read-through behavior.
    """

    def __init__(
        self,
        mem_limit_bytes: int = 64 << 20,
        mem_max_entry: int = 1 << 20,
        disk_dir: str | None = None,
        disk_limit_bytes: int = 1 << 30,
    ):
        self.mem = MemoryChunkCache(mem_limit_bytes, mem_max_entry)
        self.disk = (
            DiskChunkCache(disk_dir, disk_limit_bytes) if disk_dir else None
        )

    def get(self, fid: str) -> bytes | None:
        data = self.mem.get(fid)
        if data is None and self.disk is not None:
            data = self.disk.get(fid)
            if data is not None:
                self.mem.set(fid, data)
        CHUNK_CACHE_COUNTER.labels(
            "hit" if data is not None else "miss"
        ).inc()
        return data

    def set(self, fid: str, data: bytes) -> None:
        if not self.mem.set(fid, data) and self.disk is not None:
            self.disk.set(fid, data)
