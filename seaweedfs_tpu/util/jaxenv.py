"""JAX backend-environment helpers.

The one non-obvious piece: this image's axon sitecustomize wraps
``jax._src.xla_bridge._get_backend_uncached`` and force-initialises the
axon PJRT client even when ``JAX_PLATFORMS=cpu`` — on a wedged device
tunnel that hangs EVERY ``jax.devices()`` call, including pure-CPU test
runs.  ``force_cpu_backend`` makes the cpu pin effective by dropping the
axon factory before any backend is touched.  Shared by tests/conftest.py
and bench.py's interpreter-mode escape hatch so the workaround cannot
drift between the two.
"""

from __future__ import annotations


def force_cpu_backend() -> None:
    """Pin jax to the CPU backend and neutralise the axon auto-init hook.

    Must run before the first backend touch (jax import is fine; the
    backend is only created lazily).
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
