"""Minimal BSON encoder/decoder (the subset MongoDB's OP_MSG needs).

No bson/pymongo library ships in this image; the mongodb filer store
speaks the wire format directly (util.mongo).  Supported types: double,
string, embedded document, array, binary (subtype 0), bool, null,
int32, int64 — everything the filemeta document model and the command
envelopes use.  Dicts preserve insertion order, as BSON requires.
"""

from __future__ import annotations

import struct


class Int64(int):
    """Marker for values that must encode as BSON int64."""


def _enc_cstring(s: str) -> bytes:
    b = s.encode()
    if b"\x00" in b:
        raise ValueError("BSON cstring cannot contain NUL")
    return b + b"\x00"


def _enc_value(name: str, v) -> bytes:
    n = _enc_cstring(name)
    if isinstance(v, bool):  # before int — bool is an int subclass
        return b"\x08" + n + (b"\x01" if v else b"\x00")
    if isinstance(v, Int64):
        return b"\x12" + n + struct.pack("<q", int(v))
    if isinstance(v, int):
        if -(1 << 31) <= v < (1 << 31):
            return b"\x10" + n + struct.pack("<i", v)
        return b"\x12" + n + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + n + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + n + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        return b"\x05" + n + struct.pack("<i", len(b)) + b"\x00" + b
    if v is None:
        return b"\x0a" + n
    if isinstance(v, dict):
        return b"\x03" + n + encode(v)
    if isinstance(v, (list, tuple)):
        inner = {str(i): x for i, x in enumerate(v)}
        return b"\x04" + n + encode(inner)
    raise TypeError(f"unsupported BSON type: {type(v)!r}")


def encode(doc: dict) -> bytes:
    body = b"".join(_enc_value(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _dec_cstring(buf: bytes, at: int) -> tuple[str, int]:
    end = buf.index(b"\x00", at)
    return buf[at:end].decode(), end + 1


def _dec_value(tag: int, buf: bytes, at: int):
    if tag == 0x01:
        return struct.unpack_from("<d", buf, at)[0], at + 8
    if tag == 0x02:
        n = struct.unpack_from("<i", buf, at)[0]
        return buf[at + 4:at + 4 + n - 1].decode(), at + 4 + n
    if tag in (0x03, 0x04):
        n = struct.unpack_from("<i", buf, at)[0]
        sub = decode(buf[at:at + n])
        if tag == 0x04:
            return [sub[str(i)] for i in range(len(sub))], at + n
        return sub, at + n
    if tag == 0x05:
        n = struct.unpack_from("<i", buf, at)[0]
        return bytes(buf[at + 5:at + 5 + n]), at + 5 + n
    if tag == 0x08:
        return buf[at] != 0, at + 1
    if tag == 0x0A:
        return None, at
    if tag == 0x10:
        return struct.unpack_from("<i", buf, at)[0], at + 4
    if tag == 0x12:
        return struct.unpack_from("<q", buf, at)[0], at + 8
    raise ValueError(f"unsupported BSON tag 0x{tag:02x}")


def decode(buf: bytes) -> dict:
    total = struct.unpack_from("<i", buf, 0)[0]
    if total > len(buf):
        raise ValueError("truncated BSON document")
    out: dict = {}
    at = 4
    while buf[at] != 0:
        tag = buf[at]
        name, at = _dec_cstring(buf, at + 1)
        out[name], at = _dec_value(tag, buf, at)
    return out
