"""`weed scaffold` — emit default TOML config files.

Reference: weed/command/scaffold.go:13 (the templates themselves are
redesigned for this framework: python store backends, the tpu ec codec
section, and the maintenance scripts that our shell actually implements).
"""

from __future__ import annotations

SECURITY_TOML = '''\
# security.toml
# Discovered from ./, ~/.seaweedfs/, /usr/local/etc/seaweedfs/,
# /etc/seaweedfs/. All sections are optional; empty values disable the
# feature.

[jwt.signing]
# When set, the master mints a JWT with each assignment and volume
# servers require it on writes (flag -jwtKey overrides).
key = ""

[guard]
# Source-IP whitelist for volume-server writes (flag -whiteList overrides).
white_list = []

# gRPC mTLS: every component presents a cert signed by the shared CA and
# verifies its peers. Generate a dev set with:
#   python -c "from seaweedfs_tpu.security import generate_dev_certs; \\
#              generate_dev_certs('certs')"
[grpc]
ca = ""

[grpc.master]
cert = ""
key  = ""

[grpc.volume]
cert = ""
key  = ""

[grpc.filer]
cert = ""
key  = ""

[grpc.broker]
cert = ""
key  = ""

[grpc.client]
cert = ""
key  = ""
'''

MASTER_TOML = '''\
# master.toml

[master.maintenance]
# Admin-shell lines the leader runs under the exclusive admin lock.
scripts = [
  "ec.encode -fullPercent=95 -quietFor=1h",
  "ec.rebuild -force",
  "ec.balance -force",
  "volume.balance -force",
  "volume.fix.replication",
]
# Seconds between runs (the reference's default is ~17 minutes).
periodic_seconds = 1020

[master.sequencer]
# memory | snowflake | etcd
type = "memory"
# etcd kind: comma-separated etcd v3 endpoints (framework-native client)
sequencer_etcd_urls = "127.0.0.1:2379"
# Unique per-master worker id stamped into snowflake file ids.
sequencer_snowflake_id = 0

# The erasure-coding codec volume servers use for bulk encode/rebuild
# (flag -ec.codec overrides).
[codec]
# auto | cpu | tpu | tpu_xor | tpu_mxu — auto probes one timed encode
# round trip and picks the faster of the device and host-SIMD codecs
# for this machine.
type = "auto"
'''

FILER_TOML = '''\
# filer.toml
# Exactly one enabled store backend.

[memory]
# In-process, non-persistent; tests only.
enabled = false

[sqlite]
enabled = true
dbFile = "./filer.db"

[leveldb]
# Embedded sorted-file store (pure python SSTable-style).
enabled = false
dir = "./filerldb"

[leveldb2]
# Same store, md5-hash-partitioned into 8 instances (dir/00..07).
enabled = false
dir = "./filerldb2"

[leveldb3]
# Adaptive per-bucket partitioning: /buckets/<b> objects get their own
# DB; dropping a bucket is O(1).
enabled = false
dir = "./filerldb3"

[redis]
# Any RESP2 endpoint (framework-native client, no redis library).
enabled = false
host = "127.0.0.1"
port = 6379
db = 0

[etcd]
# etcd v3 cluster (framework-native gRPC KV client, no etcd library).
enabled = false
servers = "127.0.0.1:2379"

[elastic7]
# Elasticsearch 7 (framework-native REST client, no ES library).
enabled = false
servers = "http://127.0.0.1:9200"
username = ""
password = ""

[mongodb]
# MongoDB 3.6+ (framework-native OP_MSG wire client, no pymongo).
enabled = false
host = "127.0.0.1"
port = 27017
database = "seaweedfs"

[cassandra]
# Cassandra (framework-native CQL v4 client, no driver library).
# Expects: CREATE TABLE seaweedfs.filemeta (directory blob, name blob,
#   meta blob, PRIMARY KEY (directory, name));
enabled = false
host = "127.0.0.1"
port = 9042
keyspace = "seaweedfs"

[mysql]
# Needs the pymysql (or mysqlclient) driver installed.
enabled = false
hostname = "localhost"
port = 3306
username = "root"
password = ""
database = "seaweedfs"

[postgres]
# Needs the psycopg2 driver installed.
enabled = false
hostname = "localhost"
port = 5432
username = "postgres"
password = ""
database = "seaweedfs"
'''

NOTIFICATION_TOML = '''\
# notification.toml
# Filer metadata events fan out to at most one enabled queue
# (weed scaffold -config=notification analogue).

[notification.log]
# Print events to the filer's log.
enabled = false

[notification.file]
# Append JSON events to a local file.
enabled = false
path = "./filer_events.jsonl"

[notification.kafka]
# Needs a reachable Kafka broker.
enabled = false
hosts = "kafka1:9092"
topic = "seaweedfs_filer"

[notification.aws_sqs]
# Signed with the framework's own SigV4; no AWS SDK required.
enabled = false
aws_access_key_id = ""
aws_secret_access_key = ""
region = "us-east-2"
sqs_queue_url = ""

# (google_pub_sub exists in code but needs a programmatic OAuth token
# source, which a static TOML cannot supply — configure it in-process.)
'''

REPLICATION_TOML = '''\
# replication.toml
# Where `filer.replicate` replays filer events; one enabled sink.

[source.filer]
enabled = true
grpcAddress = "localhost:18888"
directory = "/buckets"

[sink.local]
enabled = false
directory = "/backup"

[sink.filer]
enabled = false
grpcAddress = "localhost:18888"
directory = "/backup"

[sink.s3]
# Any S3-compatible endpoint (framework-native SigV4 client).
enabled = false
endpoint = "localhost:8333"
bucket = "backup"
directory = ""

[sink.google_cloud_storage]
# HMAC interoperability credentials (S3-compat XML API).
enabled = false
bucket = ""
access_key = ""
secret_key = ""
directory = ""

[sink.azure]
enabled = false
account_name = ""
account_key = ""
container = ""
directory = ""

[sink.backblaze]
enabled = false
b2_account_id = ""
b2_master_application_key = ""
region = "us-west-002"
bucket = ""
directory = ""
'''

SHELL_TOML = '''\
# shell.toml
# Defaults for `weed shell` when -master/-filer flags are omitted.

[cluster.default]
master = "localhost:9333"
filer = "localhost:8888"
'''

TEMPLATES = {
    "security": SECURITY_TOML,
    "master": MASTER_TOML,
    "filer": FILER_TOML,
    "notification": NOTIFICATION_TOML,
    "replication": REPLICATION_TOML,
    "shell": SHELL_TOML,
}


def scaffold(config: str) -> str:
    if config not in TEMPLATES:
        raise ValueError(
            f"unknown config {config!r}; one of {sorted(TEMPLATES)}")
    return TEMPLATES[config]
