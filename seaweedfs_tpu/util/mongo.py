"""Framework-native MongoDB wire client (OP_MSG) + in-process fake.

No pymongo ships in this image, so — like the RESP, etcd-v3 and ES REST
clients before it — the mongodb filer store speaks the wire protocol
itself: OP_MSG (opcode 2013, MongoDB 3.6+) request/reply framing around
BSON command documents (util.bsonlite).  `FakeMongoServer` implements
the same command subset (find / update-upsert / delete, with $or /
$gte / $lt / $gt filters, sort + limit) over a dict, proving the
client's framing and command shapes without the external service.
"""

from __future__ import annotations

import socket
import struct
import threading

from . import bsonlite

OP_MSG = 2013


def _frame(request_id: int, doc: dict) -> bytes:
    body = b"\x00\x00\x00\x00" + b"\x00" + bsonlite.encode(doc)
    header = struct.pack("<iiii", 16 + len(body), request_id, 0, OP_MSG)
    return header + body


def _read_exact(sock: socket.socket, n: int) -> bytes:
    from .netio import read_exact

    return read_exact(sock, n, "mongo")


def _read_msg(sock: socket.socket) -> dict:
    length, _rid, _to, opcode = struct.unpack("<iiii", _read_exact(sock, 16))
    payload = _read_exact(sock, length - 16)
    if opcode != OP_MSG:
        raise IOError(f"unexpected mongo opcode {opcode}")
    # flagBits(4) + section kind byte(1) + body document
    return bsonlite.decode(payload[5:])


class MongoClient:
    """One command round trip per call over a pooled connection."""

    def __init__(self, host: str = "127.0.0.1", port: int = 27017,
                 database: str = "seaweedfs", timeout: float = 10.0):
        self.host, self.port = host, port
        self.database = database
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._rid = 0

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def command(self, doc: dict) -> dict:
        doc = dict(doc)
        doc["$db"] = self.database
        with self._lock:
            self._rid += 1
            try:
                sock = self._conn()
                sock.sendall(_frame(self._rid, doc))
                resp = _read_msg(sock)
            except (OSError, ConnectionError):
                self.close()  # reconnect once on a stale pooled socket
                sock = self._conn()
                sock.sendall(_frame(self._rid, doc))
                resp = _read_msg(sock)
        if resp.get("ok") != 1 and resp.get("ok") != 1.0:
            raise IOError(f"mongo command failed: {resp}")
        return resp

    def find(self, collection: str, flt: dict, sort: dict | None = None,
             limit: int = 101) -> list[dict]:
        """Bounded find: singleBatch with batchSize == limit, so a real
        mongod returns everything the caller asked for in one reply.
        Callers must always bound their queries (unbounded iteration
        would need getMore cursor paging, which nothing here requires)."""
        if limit <= 0:
            raise ValueError("find() requires a positive limit")
        cmd: dict = {"find": collection, "filter": flt,
                     "singleBatch": True, "batchSize": limit,
                     "limit": limit}
        if sort:
            cmd["sort"] = sort
        resp = self.command(cmd)
        return resp.get("cursor", {}).get("firstBatch", [])

    def upsert(self, collection: str, flt: dict, update_set: dict) -> None:
        self.command({"update": collection, "updates": [
            {"q": flt, "u": {"$set": update_set}, "upsert": True},
        ]})

    def delete(self, collection: str, flt: dict, many: bool = False) -> int:
        resp = self.command({"delete": collection, "deletes": [
            {"q": flt, "limit": 0 if many else 1},
        ]})
        return int(resp.get("n", 0))

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# ---------------------------------------------------------------------------
# Fake server
# ---------------------------------------------------------------------------


def _match(doc: dict, flt: dict) -> bool:
    for k, cond in flt.items():
        if k == "$or":
            if not any(_match(doc, sub) for sub in cond):
                return False
            continue
        val = doc.get(k)
        if isinstance(cond, dict) and any(op.startswith("$")
                                          for op in cond):
            for op, bound in cond.items():
                if op == "$gt" and not (val is not None and val > bound):
                    return False
                if op == "$gte" and not (val is not None and val >= bound):
                    return False
                if op == "$lt" and not (val is not None and val < bound):
                    return False
                if op == "$lte" and not (val is not None and val <= bound):
                    return False
                if op == "$eq" and val != bound:
                    return False
        elif val != cond:
            return False
    return True


class FakeMongoServer:
    """OP_MSG find/update/delete over in-memory collections."""

    def __init__(self, port: int = 0):
        self.port = port
        self._collections: dict[str, list[dict]] = {}
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self._stop = threading.Event()

    def _handle_cmd(self, cmd: dict) -> dict:
        with self._lock:
            if "find" in cmd:
                rows = [d for d in self._collections.get(cmd["find"], [])
                        if _match(d, cmd.get("filter", {}))]
                for field, order in reversed(
                        list(cmd.get("sort", {}).items())):
                    rows.sort(key=lambda d: d.get(field, ""),
                              reverse=(order == -1))
                limit = int(cmd.get("limit", 0))
                if limit:
                    rows = rows[:limit]
                return {"cursor": {"firstBatch": rows, "id": bsonlite.Int64(0),
                                   "ns": f"x.{cmd['find']}"}, "ok": 1.0}
            if "update" in cmd:
                col = self._collections.setdefault(cmd["update"], [])
                n = 0
                for u in cmd.get("updates", []):
                    hit = [d for d in col if _match(d, u.get("q", {}))]
                    if hit:
                        for d in hit:
                            d.update(u["u"].get("$set", {}))
                            n += 1
                    elif u.get("upsert"):
                        doc = dict(u.get("q", {}))
                        doc = {k: v for k, v in doc.items()
                               if not isinstance(v, dict)}
                        doc.update(u["u"].get("$set", {}))
                        col.append(doc)
                        n += 1
                return {"n": n, "ok": 1.0}
            if "delete" in cmd:
                col = self._collections.get(cmd["delete"], [])
                n = 0
                for spec in cmd.get("deletes", []):
                    flt, lim = spec.get("q", {}), spec.get("limit", 0)
                    keep = []
                    for d in col:
                        if _match(d, flt) and (lim == 0 or n < lim):
                            n += 1
                        else:
                            keep.append(d)
                    col[:] = keep
                return {"n": n, "ok": 1.0}
            return {"ok": 1.0}  # ping/ismaster/etc.

    def _client_loop(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                try:
                    length, rid, _to, _op = struct.unpack(
                        "<iiii", _read_exact(conn, 16))
                    payload = _read_exact(conn, length - 16)
                except (ConnectionError, struct.error, OSError):
                    return
                cmd = bsonlite.decode(payload[5:])
                reply = self._handle_cmd(cmd)
                body = b"\x00\x00\x00\x00\x00" + bsonlite.encode(reply)
                conn.sendall(struct.pack(
                    "<iiii", 16 + len(body), 0, rid, OP_MSG) + body)
        finally:
            conn.close()

    def start(self) -> None:
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind(("127.0.0.1", self.port))
        self._srv.listen(16)
        self.port = self._srv.getsockname()[1]

        def accept_loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except OSError:
                    return
                threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
