"""AES-256-GCM chunk encryption.

Reference: weed/util/cipher.go — each chunk gets its own random 32-byte
key stored in the chunk's metadata (FileChunk.cipher_key); the stored
blob is nonce || ciphertext || tag, so possession of the volume files
alone reveals nothing.  Wire layout matches the reference (gcm.Seal with
the nonce prepended), standard 12-byte GCM nonce and 16-byte tag.
"""

from __future__ import annotations

import os

try:  # gated: hosts without the cryptography wheel can still run the
    # plaintext path (maybe_seal(enabled=False)); only actually sealing
    # or opening a sealed chunk requires the dependency
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
except ImportError:  # pragma: no cover - environment-dependent
    AESGCM = None

KEY_SIZE = 32
NONCE_SIZE = 12


def _require_aesgcm():
    if AESGCM is None:
        raise RuntimeError(
            "chunk encryption requires the 'cryptography' package, "
            "which is not installed on this host")
    return AESGCM


def gen_cipher_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    nonce = os.urandom(NONCE_SIZE)
    return nonce + _require_aesgcm()(key).encrypt(nonce, plaintext, None)


def decrypt(blob: bytes, key: bytes) -> bytes:
    if len(blob) < NONCE_SIZE:
        raise ValueError("ciphertext too short")
    return _require_aesgcm()(key).decrypt(
        blob[:NONCE_SIZE], blob[NONCE_SIZE:], None)


def maybe_seal(data: bytes, enabled: bool) -> tuple[bytes, bytes]:
    """-> (stored_bytes, cipher_key): seal with a fresh per-chunk key
    when enabled, pass through otherwise.  Shared by every chunk writer
    (filer autochunk, FUSE mount) so the sealing format cannot drift."""
    if not enabled:
        return data, b""
    key = gen_cipher_key()
    return encrypt(data, key), key
