"""Chunk compression: gzip + zstd with content-aware gating.

Reference: weed/util/compression.go — IsGzippable decides by mime/ext,
compression happens per uploaded chunk and is recorded so reads can
transparently decompress.
"""

from __future__ import annotations

import gzip

try:
    import zstandard as _zstd
except ImportError:  # pragma: no cover - zstd ships in this image
    _zstd = None

_COMPRESSIBLE_MIME_PREFIXES = ("text/",)
_COMPRESSIBLE_MIMES = {
    "application/json", "application/javascript", "application/xml",
    "application/xhtml+xml", "application/x-javascript",
}
_COMPRESSIBLE_EXTS = {
    ".txt", ".log", ".csv", ".json", ".js", ".css", ".html", ".htm",
    ".xml", ".md", ".py", ".go", ".java", ".c", ".cc", ".h", ".sql",
}
_INCOMPRESSIBLE_EXTS = {
    ".gz", ".zst", ".zip", ".bz2", ".xz", ".7z", ".png", ".jpg",
    ".jpeg", ".gif", ".webp", ".mp3", ".mp4", ".mov", ".avi",
}


def is_compressible(filename: str = "", mime: str = "") -> bool:
    """util/compression.go IsGzippableFileType."""
    ext = ""
    if "." in filename:
        ext = filename[filename.rfind("."):].lower()
    if ext in _INCOMPRESSIBLE_EXTS:
        return False
    if ext in _COMPRESSIBLE_EXTS:
        return True
    if mime:
        if any(mime.startswith(p) for p in _COMPRESSIBLE_MIME_PREFIXES):
            return True
        if mime.split(";")[0].strip() in _COMPRESSIBLE_MIMES:
            return True
    return False


def gzip_data(data: bytes) -> bytes:
    return gzip.compress(data, compresslevel=3)


def gunzip_data(data: bytes) -> bytes:
    return gzip.decompress(data)


def zstd_available() -> bool:
    return _zstd is not None


def zstd_data(data: bytes) -> bytes:
    if _zstd is None:
        raise RuntimeError("zstandard not available")
    return _zstd.ZstdCompressor(level=3).compress(data)


def unzstd_data(data: bytes) -> bytes:
    if _zstd is None:
        raise RuntimeError("zstandard not available")
    return _zstd.ZstdDecompressor().decompress(data)


def compress_if_worthwhile(data: bytes, filename: str = "",
                           mime: str = "") -> tuple[bytes, bool]:
    """-> (maybe_compressed, was_compressed); keeps the original unless
    gzip actually shrinks it (compression.go MaybeGzipData)."""
    if not is_compressible(filename, mime) or len(data) < 128:
        return data, False
    packed = gzip_data(data)
    if len(packed) >= len(data):
        return data, False
    return packed, True
