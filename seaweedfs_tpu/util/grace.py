"""Process profiling hooks.

Reference: weed/util/grace (the -cpuprofile/-memprofile flags every
server command exposes, command/volume.go:117-120) plus the optional
net/http/pprof handlers.  Python equivalents: cProfile for CPU (pstats
dump written at exit) and tracemalloc for memory (top-allocations
snapshot at exit); `profile_status()` backs a /debug/profile endpoint.
"""

from __future__ import annotations

import atexit
import cProfile
import io

_cpu_profiler: cProfile.Profile | None = None


def setup_profiling(cpuprofile: str = "", memprofile: str = "") -> None:
    """Arm CPU and/or memory profiling; results land in the given files
    when the process exits."""
    global _cpu_profiler
    if cpuprofile and _cpu_profiler is None:
        prof = cProfile.Profile()
        prof.enable()
        _cpu_profiler = prof

        def _dump_cpu() -> None:
            try:
                prof.disable()
            except Exception:
                pass
            prof.dump_stats(cpuprofile)

        atexit.register(_dump_cpu)
    if memprofile:
        import tracemalloc

        tracemalloc.start(25)

        def _dump_mem() -> None:
            snap = tracemalloc.take_snapshot()
            with open(memprofile, "w") as f:
                for stat in snap.statistics("lineno")[:100]:
                    f.write(f"{stat}\n")

        atexit.register(_dump_mem)


def profile_status() -> dict:
    """Live profiling numbers for a /debug endpoint."""
    import gc
    import resource
    import threading

    ru = resource.getrusage(resource.RUSAGE_SELF)
    out = {
        "max_rss_kb": ru.ru_maxrss,
        "user_cpu_s": round(ru.ru_utime, 3),
        "system_cpu_s": round(ru.ru_stime, 3),
        "threads": threading.active_count(),
        "gc_objects": len(gc.get_objects()),
        "cpu_profiler_armed": _cpu_profiler is not None,
    }
    try:
        import tracemalloc

        if tracemalloc.is_tracing():
            current, peak = tracemalloc.get_traced_memory()
            out["traced_current_bytes"] = current
            out["traced_peak_bytes"] = peak
    except ImportError:
        pass
    return out
