"""Shared HTTP plumbing for the http.server-based gateways.

Reference analogue: weed/util/http_util.go (request helpers shared by
every server).
"""

from __future__ import annotations


def read_chunked_body(rfile, max_bytes: int = 1 << 30) -> bytes:
    """Decode a Transfer-Encoding: chunked request body.

    Raises ValueError on a malformed or truncated stream — callers must
    answer 400, never store a silently-truncated body.  Trailer headers
    after the last chunk are consumed so a keep-alive connection stays
    framed correctly.
    """
    out = bytearray()
    while True:
        size_line = rfile.readline()
        if not size_line:
            raise ValueError("chunked body: EOF before last chunk")
        try:
            size = int(size_line.strip().split(b";")[0], 16)
        except ValueError:
            raise ValueError(
                f"chunked body: bad chunk size {size_line[:20]!r}")
        if size == 0:
            break
        if len(out) + size > max_bytes:
            raise ValueError("chunked body: too large")
        data = rfile.read(size)
        if len(data) < size:
            raise ValueError("chunked body: truncated chunk")
        out += data
        crlf = rfile.read(2)
        if crlf not in (b"\r\n", b"\n"):
            raise ValueError("chunked body: missing chunk CRLF")
    # consume optional trailer section up to the blank line
    while True:
        line = rfile.readline()
        if line in (b"\r\n", b"\n", b""):
            break
    return bytes(out)


def trace_headers(headers: dict | None = None) -> dict:
    """Copy of `headers` with the active W3C `traceparent` injected.

    The one helper every outgoing HTTP request in the framework routes
    through, so a client write yields a connected trace across
    filer -> master -> volume -> replication hops."""
    from ..telemetry import trace

    out = dict(headers or {})
    trace.inject_headers(out)
    return out


def netloc(url: str) -> str:
    """host:port of a URL (or of a bare host:port string) — the breaker /
    location-cache key every failover path shares."""
    import urllib.parse

    if "//" not in url:
        return url.split("/", 1)[0]
    return urllib.parse.urlsplit(url).netloc


GRPC_PORT_OFFSET = 10000


def grpc_address(http_address: str, offset: int = GRPC_PORT_OFFSET) -> str:
    """Every server exposes gRPC at http_port + 10000 (the convention the
    reference sets with its -port.grpc defaults)."""
    host, _, port = http_address.partition(":")
    return f"{host}:{int(port) + offset}"
