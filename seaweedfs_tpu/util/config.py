"""TOML configuration tier.

Reference: weed/util/config.go:20-48 — config files named <name>.toml are
discovered in the working directory, then ~/.seaweedfs/, then
/usr/local/etc/seaweedfs/, then /etc/seaweedfs/; flags stay the primary
knob and the TOML tier supplies the structured parts (security certs,
store backends, maintenance scripts).

Python's stdlib tomllib replaces viper; keys are accessed with the same
dotted-path convention ("grpc.ca", "jwt.signing.key") the reference uses.
"""

from __future__ import annotations

import os

try:  # stdlib on 3.11+; gated so 3.10 hosts still run (a missing TOML
    # parser only matters when a .toml file is actually present)
    import tomllib
except ImportError:  # pragma: no cover - environment-dependent
    try:
        import tomli as tomllib  # the 3.10 backport, if installed
    except ImportError:
        tomllib = None

SEARCH_PATHS = (
    ".",
    os.path.expanduser("~/.seaweedfs"),
    "/usr/local/etc/seaweedfs",
    "/etc/seaweedfs",
)


class Configuration:
    """A loaded TOML document with dotted-key access."""

    def __init__(self, data: dict | None = None, path: str = ""):
        self.data = data or {}
        self.path = path  # file it came from ("" = not found)

    @property
    def loaded(self) -> bool:
        return bool(self.path)

    def get(self, dotted_key: str, default=None):
        node = self.data
        for part in dotted_key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_string(self, key: str, default: str = "") -> str:
        v = self.get(key, default)
        return v if isinstance(v, str) else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        return v if isinstance(v, bool) else default

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key, default)
        return v if isinstance(v, int) and not isinstance(v, bool) else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key, default)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return default

    def get_list(self, key: str, default: list | None = None) -> list:
        v = self.get(key)
        return v if isinstance(v, list) else (default or [])


def load_configuration(
    name: str, required: bool = False, search_paths=SEARCH_PATHS
) -> Configuration:
    """Find and parse <name>.toml along the search path."""
    for d in search_paths:
        path = os.path.join(d, f"{name}.toml")
        if os.path.isfile(path):
            if tomllib is None:
                raise RuntimeError(
                    f"found {path} but no TOML parser is available "
                    "(python < 3.11 without the tomli backport)")
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), path=path)
    if required:
        raise FileNotFoundError(
            f"{name}.toml not found in {', '.join(search_paths)}; generate "
            f"a default with: weed scaffold -config={name} -output=."
        )
    return Configuration()
