"""Minimal RESP2 (Redis protocol) client — no client library needed.

Reference analogue: the go-redis dependency behind weed/filer/redis.
Only the handful of commands the redis filer store uses; one socket per
client with a lock (the filer store serializes through it).
"""

from __future__ import annotations

import socket
import threading
from ..util.httpd import LISTEN_BACKLOG


class RespError(RuntimeError):
    pass


class RespClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 db: int = 0, timeout: float = 10.0):
        self.host, self.port, self.db = host, port, db
        self.timeout = timeout
        self._lock = threading.Lock()
        self._sock = None
        self._f = None
        self._connect()

    def _connect(self) -> None:
        self._teardown()
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self._f = self._sock.makefile("rb")
        if self.db:
            self._send_locked("SELECT", str(self.db))

    def _teardown(self) -> None:
        for h in (self._f, self._sock):
            try:
                if h:
                    h.close()
            except OSError:
                pass
        self._f = self._sock = None

    def close(self) -> None:
        with self._lock:
            self._teardown()

    def _send_locked(self, *parts: str | bytes):
        out = [f"*{len(parts)}\r\n".encode()]
        for p in parts:
            b = p if isinstance(p, bytes) else str(p).encode()
            out.append(f"${len(b)}\r\n".encode())
            out.append(b + b"\r\n")
        self._sock.sendall(b"".join(out))
        return self._read_reply()

    def command(self, *parts: str | bytes):
        """Send one command, return the parsed reply.

        A transport failure (dropped connection, timeout — the stream is
        desynchronized after either) tears the socket down and retries
        ONCE on a fresh connection; the server must not stay wedged on
        one redis restart."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                return self._send_locked(*parts)
            except (OSError, RespError) as e:
                if isinstance(e, RespError) and \
                        "connection closed" not in str(e):
                    raise  # a real -ERR reply, not a transport failure
                self._teardown()
                self._connect()
                return self._send_locked(*parts)

    def _read_reply(self):
        line = self._f.readline()
        if not line:
            raise RespError("connection closed")
        kind, rest = line[:1], line[1:].rstrip(b"\r\n")
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            data = self._f.read(n + 2)
            return data[:-2]
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"bad reply type {kind!r}")


class FakeRedisServer:
    """In-process RESP2 server covering the commands the redis filer
    store issues — the test double standing in for a real redis (this
    image ships no redis server)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import socketserver

        self.kv: dict[bytes, bytes] = {}
        self.sets: dict[bytes, set[bytes]] = {}
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    try:
                        cmd = self._read_command()
                    except (ValueError, OSError):
                        return
                    if cmd is None:
                        return
                    self._dispatch([bytes(c) for c in cmd])

            def _read_command(self):
                line = self.rfile.readline()
                if not line:
                    return None
                if not line.startswith(b"*"):
                    raise ValueError("inline commands unsupported")
                n = int(line[1:])
                parts = []
                for _ in range(n):
                    hdr = self.rfile.readline()
                    size = int(hdr[1:])
                    parts.append(self.rfile.read(size + 2)[:-2])
                return parts

            def _send(self, payload: bytes):
                self.wfile.write(payload)
                self.wfile.flush()

            def _bulk(self, b):
                if b is None:
                    return self._send(b"$-1\r\n")
                self._send(f"${len(b)}\r\n".encode() + b + b"\r\n")

            def _dispatch(self, cmd):
                op = cmd[0].upper()
                with outer._lock:
                    if op == b"PING":
                        return self._send(b"+PONG\r\n")
                    if op == b"SELECT":
                        return self._send(b"+OK\r\n")
                    if op == b"SET":
                        outer.kv[cmd[1]] = cmd[2]
                        return self._send(b"+OK\r\n")
                    if op == b"GET":
                        return self._bulk(outer.kv.get(cmd[1]))
                    if op == b"DEL":
                        n = 0
                        for k in cmd[1:]:
                            n += 1 if outer.kv.pop(k, None) is not None else 0
                            n += 1 if outer.sets.pop(k, None) is not None else 0
                        return self._send(f":{n}\r\n".encode())
                    if op == b"SADD":
                        s = outer.sets.setdefault(cmd[1], set())
                        added = sum(1 for m in cmd[2:] if m not in s)
                        s.update(cmd[2:])
                        return self._send(f":{added}\r\n".encode())
                    if op == b"SREM":
                        s = outer.sets.get(cmd[1], set())
                        removed = sum(1 for m in cmd[2:] if m in s)
                        s.difference_update(cmd[2:])
                        return self._send(f":{removed}\r\n".encode())
                    if op == b"KEYS":
                        rx = outer._glob_to_regex(cmd[1])
                        keys = sorted({
                            k for k in list(outer.kv) + list(outer.sets)
                            if rx.fullmatch(k)})
                        out = [f"*{len(keys)}\r\n".encode()]
                        for k in keys:
                            out.append(f"${len(k)}\r\n".encode() + k + b"\r\n")
                        return self._send(b"".join(out))
                    if op == b"SMEMBERS":
                        members = sorted(outer.sets.get(cmd[1], set()))
                        out = [f"*{len(members)}\r\n".encode()]
                        for m in members:
                            out.append(f"${len(m)}\r\n".encode() + m + b"\r\n")
                        return self._send(b"".join(out))
                    return self._send(b"-ERR unknown command\r\n")

        class Server(socketserver.ThreadingTCPServer):
            request_queue_size = LISTEN_BACKLOG
            allow_reuse_address = True
            daemon_threads = True

        self._lock = threading.Lock()
        self._server = Server((host, port), Handler)
        self.port = self._server.server_address[1]

    @staticmethod
    def _glob_to_regex(pattern: bytes):
        """Redis KEYS glob -> regex, honoring backslash escapes (which
        fnmatch lacks): *, ?, [...] and backslash-quoted literals."""
        import re

        out = []
        i = 0
        while i < len(pattern):
            ch = pattern[i : i + 1]
            if ch == b"\\" and i + 1 < len(pattern):
                out.append(re.escape(pattern[i + 1 : i + 2]))
                i += 2
                continue
            if ch == b"*":
                out.append(b".*")
            elif ch == b"?":
                out.append(b".")
            elif ch == b"[":
                j = pattern.find(b"]", i + 1)
                if j == -1:
                    out.append(re.escape(ch))
                else:
                    out.append(pattern[i : j + 1])
                    i = j
            else:
                out.append(re.escape(ch))
            i += 1
        return re.compile(b"".join(out), re.DOTALL)

    def start(self) -> None:
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
