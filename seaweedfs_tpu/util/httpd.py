"""Shared HTTP server base for every gateway/server in the framework.

``http.server``'s default listen backlog (request_queue_size) is 5 — a
burst of concurrent clients (the reference benchmark's c=16, replication
fan-out storms) overflows it and the kernel resets connections that never
reach accept().  One subclass fixes the backlog for all eight HTTP surfaces
(master/volume/filer/s3/iam/webdav/gateway/metrics); the raw-TCP
listeners (volume TCP data path, RESP test server, FTP control port)
apply the same backlog to their ThreadingTCPServer subclasses.

TCP_NODELAY is set on every accepted connection: with Nagle on, a
keep-alive request/response exchange stalls ~40ms per round trip
(Nagle x delayed-ACK interaction) — measured as a 120x small-file
throughput cliff (363 req/s -> 44k req/s at c=16x1KB on loopback).
The reference's Go net/http enables it by default.
"""

from __future__ import annotations

import socket
from http.server import ThreadingHTTPServer

LISTEN_BACKLOG = 128


class FrameworkHTTPServer(ThreadingHTTPServer):
    request_queue_size = LISTEN_BACKLOG

    def process_request(self, request, client_address):
        try:
            request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. AF_UNIX test sockets
        super().process_request(request, client_address)


def drain_request_body(handler, cap: int = 1 << 20) -> None:
    """Discard an unneeded request body in bounded chunks so the next
    request on a keep-alive connection doesn't parse leftover payload
    bytes as a request line; bodies over `cap` (or chunked bodies) close
    the connection instead of buffering gigabytes to throw away.  The
    one early-reply body-hygiene helper for every handler class."""
    te = (handler.headers.get("Transfer-Encoding") or "").lower()
    if "chunked" in te:
        handler.close_connection = True
        return
    try:
        length = int(handler.headers.get("Content-Length") or 0)
    except ValueError:
        length = 0
    if length > cap:
        handler.close_connection = True
        return
    while length > 0:
        chunk = handler.rfile.read(min(length, 1 << 16))
        if not chunk:
            break
        length -= len(chunk)


def shield_handler(cls, send_json_attr: str) -> None:
    """Wrap a BaseHTTPRequestHandler subclass's do_* verbs so an
    unhandled exception answers 500 (via the named send-json method)
    instead of slamming the socket shut.  The connection always closes
    after a shielded exception: if part of a response already went out,
    appending a 500 would corrupt the keep-alive stream, so the client
    must re-dial either way."""
    from . import glog

    def wrap(name: str):
        inner = getattr(cls, name)

        def safe(self):
            try:
                inner(self)
            except (BrokenPipeError, ConnectionResetError):
                raise  # the CLIENT went away; nothing to answer
            except Exception as e:  # noqa: BLE001 — boundary guard
                glog.warning("%s %s failed: %r", name[3:], self.path, e)
                try:
                    getattr(self, send_json_attr)(500, {"error": str(e)})
                except Exception:
                    pass  # headers already sent / socket gone
                self.close_connection = True

        safe.__name__ = name
        setattr(cls, name, safe)

    for name in ("do_GET", "do_HEAD", "do_POST", "do_PUT", "do_DELETE"):
        if hasattr(cls, name):
            wrap(name)
