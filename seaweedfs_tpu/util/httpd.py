"""Shared HTTP server base for every gateway/server in the framework.

``http.server``'s default listen backlog (request_queue_size) is 5 — a
burst of concurrent clients (the reference benchmark's c=16, replication
fan-out storms) overflows it and the kernel resets connections that never
reach accept().  One subclass fixes the backlog for all eight HTTP surfaces
(master/volume/filer/s3/iam/webdav/gateway/metrics); the raw-TCP
listeners (volume TCP data path, RESP test server, FTP control port)
apply the same backlog to their ThreadingTCPServer subclasses.
"""

from __future__ import annotations

from http.server import ThreadingHTTPServer

LISTEN_BACKLOG = 128


class FrameworkHTTPServer(ThreadingHTTPServer):
    request_queue_size = LISTEN_BACKLOG
