"""Shared HTTP serving plane for every gateway/server in the framework.

Two front ends behind one `make_http_server` seam:

* ``FrameworkHTTPServer`` — the thread-per-connection fallback
  (``ThreadingHTTPServer`` + a real listen backlog + TCP_NODELAY).
  A keep-alive connection pins one thread for its whole life, so
  thousands of mostly-idle sockets mean thousands of threads.

* ``EventLoopHTTPServer`` — a ``selectors`` event loop owns every
  socket while it is idle: one thread accepts, accumulates request
  headers non-blocking, and only hands a connection to a BOUNDED worker
  pool once a full request head has arrived.  The worker reuses the
  ordinary ``BaseHTTPRequestHandler`` subclass for exactly ONE request
  (body reads block only that worker), then parks the socket back on
  the loop.  Thousands of idle keep-alive connections cost a few bytes
  of buffer each instead of a thread.  ``SEAWEEDFS_TPU_EVENTLOOP``
  selects it: ``volume`` (default — the volume data port only),
  ``all`` (every surface that routes through make_http_server), or
  ``off``.

Responses from both front ends go out through ``_BufferedSocketWriter``:
``send_response``/``send_header``/body writes coalesce and reach the
kernel as ONE ``sendmsg`` (the old unbuffered wfile paid one syscall
per header block and one per body, and the header/body split is exactly
the short-write+delayed-ACK shape Nagle punishes).

``http.server``'s default listen backlog (request_queue_size) is 5 — a
burst of concurrent clients (the reference benchmark's c=16, replication
fan-out storms) overflows it and the kernel resets connections that never
reach accept().  ``SEAWEEDFS_TPU_LISTEN_BACKLOG`` tunes the shared
backlog (default 128), clamped to the kernel's somaxconn — asking for
more than somaxconn silently truncates anyway, so the clamp keeps the
configured number honest.

TCP_NODELAY is set on every accepted connection: with Nagle on, a
keep-alive request/response exchange stalls ~40ms per round trip
(Nagle x delayed-ACK interaction) — measured as a 120x small-file
throughput cliff (363 req/s -> 44k req/s at c=16x1KB on loopback).
The reference's Go net/http enables it by default.
"""

from __future__ import annotations

import os
import selectors
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from http.server import ThreadingHTTPServer

LISTEN_BACKLOG = 128

# a request head larger than this answers 431 and closes — the loop
# must never buffer unbounded header bytes for a client that never
# sends the terminating blank line
MAX_HEADER_BYTES = 64 << 10


def _somaxconn() -> int:
    try:
        with open("/proc/sys/net/core/somaxconn") as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return getattr(socket, "SOMAXCONN", LISTEN_BACKLOG)


def listen_backlog() -> int:
    """Env-tunable listen backlog, clamped to [1, somaxconn]."""
    try:
        want = int(os.environ.get(
            "SEAWEEDFS_TPU_LISTEN_BACKLOG", str(LISTEN_BACKLOG)))
    except ValueError:
        want = LISTEN_BACKLOG
    return max(1, min(want, _somaxconn()))


def eventloop_enabled(surface: str) -> bool:
    """One flag gates the front-end choice: SEAWEEDFS_TPU_EVENTLOOP =
    "volume" (default; only the volume data port), "all", or "off"."""
    mode = os.environ.get(
        "SEAWEEDFS_TPU_EVENTLOOP", "volume").strip().lower()
    if mode in ("off", "0", "none", "false", "threaded"):
        return False
    if mode == "all":
        return True
    return surface == "volume"


def make_http_server(server_address, handler_cls, surface: str):
    """The front-end seam every serve_http goes through: an event-loop
    server when the surface opted in, the threading server otherwise.
    Both expose serve_forever/shutdown/server_close/server_address."""
    if eventloop_enabled(surface):
        return EventLoopHTTPServer(server_address, handler_cls,
                                   surface=surface)
    return FrameworkHTTPServer(server_address, handler_cls)


class FrameworkHTTPServer(ThreadingHTTPServer):
    request_queue_size = LISTEN_BACKLOG

    def __init__(self, *args, **kwargs):
        # instance attr read by TCPServer.__init__'s listen() call
        self.request_queue_size = listen_backlog()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        try:
            request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # e.g. AF_UNIX test sockets
        super().process_request(request, client_address)


def _drain_chunked(handler, cap: int) -> bool:
    """Consume a chunked request body up to `cap` payload bytes.
    -> True when fully drained (keep-alive safe), False on malformed
    framing, EOF, or overflow (caller must close the connection)."""
    total = 0
    while True:
        line = handler.rfile.readline(1024)
        if not line or not line.endswith(b"\n"):
            return False
        try:
            size = int(line.strip().split(b";")[0] or b"x", 16)
        except ValueError:
            return False
        if size == 0:
            # trailer section: lines until the terminating blank one
            while True:
                tl = handler.rfile.readline(1024)
                if tl in (b"\r\n", b"\n", b""):
                    return tl != b""
        total += size
        if total > cap:
            return False
        remaining = size + 2  # chunk bytes + trailing CRLF
        while remaining > 0:
            piece = handler.rfile.read(min(remaining, 1 << 16))
            if not piece:
                return False
            remaining -= len(piece)


def drain_request_body(handler, cap: int = 1 << 20) -> None:
    """Discard an unneeded request body in bounded chunks so the next
    request on a keep-alive connection doesn't parse leftover payload
    bytes as a request line.  Small chunked bodies are drained through
    their framing (a 100-byte chunked POST must not cost the client its
    connection); bodies over `cap` — chunked or not — close the
    connection instead of buffering gigabytes to throw away.  The one
    early-reply body-hygiene helper for every handler class."""
    te = (handler.headers.get("Transfer-Encoding") or "").lower()
    if "chunked" in te:
        if not _drain_chunked(handler, cap):
            handler.close_connection = True
        return
    try:
        length = int(handler.headers.get("Content-Length") or 0)
    except ValueError:
        length = 0
    if length > cap:
        handler.close_connection = True
        return
    while length > 0:
        chunk = handler.rfile.read(min(length, 1 << 16))
        if not chunk:
            break
        length -= len(chunk)


def shield_handler(cls, send_json_attr: str) -> None:
    """Wrap a BaseHTTPRequestHandler subclass's do_* verbs so an
    unhandled exception answers 500 (via the named send-json method)
    instead of slamming the socket shut.  The connection always closes
    after a shielded exception: if part of a response already went out,
    appending a 500 would corrupt the keep-alive stream, so the client
    must re-dial either way."""
    from . import glog

    def wrap(name: str):
        inner = getattr(cls, name)

        def safe(self):
            try:
                inner(self)
            except (BrokenPipeError, ConnectionResetError):
                raise  # the CLIENT went away; nothing to answer
            except Exception as e:  # noqa: BLE001 — boundary guard
                glog.warning("%s %s failed: %r", name[3:], self.path, e)
                try:
                    getattr(self, send_json_attr)(500, {"error": str(e)})
                except Exception:
                    pass  # headers already sent / socket gone
                self.close_connection = True

        safe.__name__ = name
        setattr(cls, name, safe)

    for name in ("do_GET", "do_HEAD", "do_POST", "do_PUT", "do_DELETE"):
        if hasattr(cls, name):
            wrap(name)


# -- single-syscall response writes ------------------------------------------


class _BufferedSocketWriter:
    """wfile replacement that coalesces the header block and body into
    ONE sendmsg per flush.  BaseHTTPRequestHandler flushes after every
    request, so a normal response costs exactly one syscall; bodies past
    the cap flush incrementally so a large GET never doubles in RAM."""

    _FLUSH_CAP = 256 << 10
    _IOV_MAX = 512  # stay far under the kernel's IOV limit

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._parts: list[bytes] = []
        self._size = 0
        self.closed = False  # socketserver's finish() checks this

    def write(self, data) -> int:
        data = bytes(data)
        if not data:
            return 0
        self._parts.append(data)
        self._size += len(data)
        # 1xx interim responses (Expect: 100-continue) must reach the
        # client NOW — it won't send the body until it sees them
        if (self._size >= self._FLUSH_CAP
                or (data[:10] in (b"HTTP/1.1 1", b"HTTP/1.0 1"))):
            self.flush()
        return len(data)

    def flush(self) -> None:
        parts, self._parts, self._size = self._parts, [], 0
        if not parts:
            return
        if len(parts) > self._IOV_MAX:
            parts = [b"".join(parts)]
        try:
            while parts:
                sent = self._sock.sendmsg(parts)
                while parts and sent >= len(parts[0]):
                    sent -= len(parts[0])
                    parts.pop(0)
                if parts and sent:
                    parts[0] = parts[0][sent:]
        except AttributeError:  # no sendmsg on this socket type
            self._sock.sendall(b"".join(parts))

    def close(self) -> None:
        self.closed = True
        try:
            self.flush()
        except OSError:
            pass  # client gone mid-flush; the socket closes right after


class BufferedResponseMixin:
    """Mixin for thread-per-connection handlers: swap the unbuffered
    makefile wfile for the coalescing writer, so even the legacy front
    end answers with a single sendmsg per response."""

    def setup(self):
        super().setup()
        self.wfile = _BufferedSocketWriter(self.connection)


# -- event-loop front end ----------------------------------------------------


class _PrefixedRFile:
    """rfile over (already-buffered header bytes + the socket).  The
    loop read the request head before dispatch; the handler re-parses it
    from this prefix, then body reads fall through to blocking recv on
    the worker.  leftover() hands unconsumed bytes (pipelined requests)
    back to the loop when the connection re-parks."""

    def __init__(self, prefix: bytes, sock: socket.socket):
        self._buf = bytearray(prefix)
        self._sock = sock
        self._eof = False

    def _more(self) -> bool:
        if self._eof:
            return False
        data = self._sock.recv(65536)  # timeout/OSError propagate
        if not data:
            self._eof = True
            return False
        self._buf += data
        return True

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            while self._more():
                pass
            out = bytes(self._buf)
            self._buf.clear()
            return out
        while len(self._buf) < n and self._more():
            pass
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def readline(self, limit: int = -1) -> bytes:
        while True:
            i = self._buf.find(b"\n")
            if i >= 0:
                end = i + 1
                if limit is not None and 0 <= limit < end:
                    end = limit
                out = bytes(self._buf[:end])
                del self._buf[:end]
                return out
            if limit is not None and 0 <= limit <= len(self._buf):
                out = bytes(self._buf[:limit])
                del self._buf[:limit]
                return out
            if not self._more():
                out = bytes(self._buf)
                self._buf.clear()
                return out

    def leftover(self) -> bytes:
        return bytes(self._buf)

    def close(self) -> None:
        pass


class _Conn:
    __slots__ = ("sock", "addr", "buf", "last")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.buf = bytearray()
        self.last = time.monotonic()


class EventLoopHTTPServer:
    """selectors-based HTTP front end: idle sockets live on the loop,
    ready requests run on a bounded worker pool through the SAME
    BaseHTTPRequestHandler subclasses the threading server uses (one
    handle_one_request per dispatch), so every handler, shield, guard
    and telemetry path is shared between front ends."""

    def __init__(self, server_address, handler_cls, surface: str = "volume"):
        from ..stats.metrics import HTTPD_INFLIGHT, HTTPD_OPEN_SOCKETS

        self.RequestHandlerClass = handler_cls
        self.surface = surface
        try:
            workers = int(os.environ.get("SEAWEEDFS_TPU_LOOP_WORKERS", "32"))
        except ValueError:
            workers = 32
        self._workers = max(1, workers)
        try:
            self._request_timeout = float(os.environ.get(
                "SEAWEEDFS_TPU_LOOP_REQUEST_TIMEOUT_S", "60"))
        except ValueError:
            self._request_timeout = 60.0
        try:
            self._idle_timeout = float(os.environ.get(
                "SEAWEEDFS_TPU_LOOP_IDLE_TIMEOUT_S", "120"))
        except ValueError:
            self._idle_timeout = 120.0
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind(server_address)
        self._listen.listen(listen_backlog())
        self._listen.setblocking(False)
        self.server_address = self._listen.getsockname()
        self._sel = selectors.DefaultSelector()
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._pool = ThreadPoolExecutor(
            max_workers=self._workers,
            thread_name_prefix=f"httpd-{surface}")
        self._rearm: deque = deque()  # conns coming back from workers
        self._shutdown_evt = threading.Event()
        self._stopped = threading.Event()
        self._conns: set[_Conn] = set()
        self._open_gauge = HTTPD_OPEN_SOCKETS.labels(surface)
        self._inflight_gauge = HTTPD_INFLIGHT.labels(surface)

    # -- loop thread ------------------------------------------------------

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        from . import glog

        self._sel.register(self._listen, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        last_sweep = time.monotonic()
        try:
            while not self._shutdown_evt.is_set():
                try:
                    events = self._sel.select(timeout=1.0)
                    for key, _mask in events:
                        tag = key.data
                        if tag == "accept":
                            self._accept()
                        elif tag == "wake":
                            self._drain_wake()
                        else:
                            self._readable(tag)
                    self._process_rearms()
                    now = time.monotonic()
                    if now - last_sweep >= 5.0:
                        self._sweep_idle(now)
                        last_sweep = now
                except OSError:
                    if self._shutdown_evt.is_set():
                        break
                    raise
                except Exception as e:  # noqa: BLE001 — loop must survive
                    glog.warning("httpd %s loop error: %r", self.surface, e)
        finally:
            self._stopped.set()

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listen.accept()
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                return
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            sock.setblocking(False)
            conn = _Conn(sock, addr)
            self._conns.add(conn)
            self._open_gauge.set(len(self._conns))
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (ValueError, KeyError, OSError):
                self._close_conn(conn, registered=False)

    def _drain_wake(self) -> None:
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, InterruptedError, OSError):
            pass

    def _readable(self, conn: _Conn) -> None:
        try:
            data = conn.sock.recv(65536)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not data:
            self._close_conn(conn)
            return
        conn.buf += data
        conn.last = time.monotonic()
        if b"\r\n\r\n" in conn.buf:
            self._dispatch(conn)
        elif len(conn.buf) > MAX_HEADER_BYTES:
            try:
                conn.sock.sendall(
                    b"HTTP/1.1 431 Request Header Fields Too Large\r\n"
                    b"Content-Length: 0\r\nConnection: close\r\n\r\n")
                # drain what the client already sent: closing with unread
                # bytes in the receive buffer RSTs the 431 off the wire
                for _ in range(64):
                    if not conn.sock.recv(65536):
                        break
            except OSError:
                pass
            self._close_conn(conn)

    def _dispatch(self, conn: _Conn) -> None:
        """Loop thread: full request head buffered — hand the socket to
        a worker.  The selector forgets it until the worker parks it
        back (or closes it)."""
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        conn.sock.settimeout(self._request_timeout)
        self._inflight_gauge.inc()
        self._pool.submit(self._handle, conn)

    def _handle(self, conn: _Conn) -> None:
        """Worker: run exactly ONE request through the handler class,
        then park the connection back on the loop (keep-alive) or close
        it."""
        keep = False
        rfile = None
        try:
            handler = self.RequestHandlerClass.__new__(
                self.RequestHandlerClass)
            handler.request = conn.sock
            handler.connection = conn.sock
            handler.client_address = conn.addr
            handler.server = self
            rfile = _PrefixedRFile(bytes(conn.buf), conn.sock)
            handler.rfile = rfile
            handler.wfile = _BufferedSocketWriter(conn.sock)
            handler.close_connection = True
            handler.handle_one_request()
            try:
                handler.wfile.flush()
            except OSError:
                handler.close_connection = True
            keep = not handler.close_connection
        except Exception:  # noqa: BLE001 — a broken conn never kills a worker
            keep = False
        finally:
            self._inflight_gauge.dec()
        if keep and not self._shutdown_evt.is_set():
            conn.buf = bytearray(rfile.leftover())
            conn.last = time.monotonic()
            try:
                conn.sock.setblocking(False)
            except OSError:
                keep = False
        if keep and not self._shutdown_evt.is_set():
            self._rearm.append(conn)
            self._wake()
        else:
            self._close_conn(conn, registered=False)

    def _process_rearms(self) -> None:
        while self._rearm:
            conn = self._rearm.popleft()
            if b"\r\n\r\n" in conn.buf:
                # a pipelined request is already complete: straight back
                # to a worker, no select round-trip
                conn.sock.settimeout(self._request_timeout)
                self._inflight_gauge.inc()
                self._pool.submit(self._handle, conn)
                continue
            try:
                self._sel.register(conn.sock, selectors.EVENT_READ, conn)
            except (ValueError, KeyError, OSError):
                self._close_conn(conn, registered=False)

    def _sweep_idle(self, now: float) -> None:
        if self._idle_timeout <= 0:
            return
        stale = [
            key.data for key in list(self._sel.get_map().values())
            if isinstance(key.data, _Conn)
            and now - key.data.last > self._idle_timeout
        ]
        for conn in stale:
            self._close_conn(conn)

    def _close_conn(self, conn: _Conn, registered: bool = True) -> None:
        if registered:
            try:
                self._sel.unregister(conn.sock)
            except (KeyError, ValueError, OSError):
                pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self._conns.discard(conn)
        self._open_gauge.set(len(self._conns))

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"x")
        except OSError:
            pass

    # -- lifecycle (ThreadingHTTPServer-compatible surface) ---------------

    def shutdown(self) -> None:
        self._shutdown_evt.set()
        self._wake()
        self._stopped.wait(5.0)

    def server_close(self) -> None:
        self._shutdown_evt.set()
        self._wake()
        try:
            self._listen.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)
        for conn in list(self._conns):
            self._close_conn(conn, registered=False)
        try:
            self._sel.close()
        except (OSError, RuntimeError):
            pass
        for s in (self._wake_r, self._wake_w):
            try:
                s.close()
            except OSError:
                pass
