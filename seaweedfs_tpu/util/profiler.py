"""Sampling thread-stack profiler behind /debug/profile.

The previous /debug/profile was a status stub (rusage + thread count) —
useful for "is it big", useless for "where is the time going".  This is
the py-spy idea without the external process: `sys._current_frames()`
returns every thread's current frame for the cost of one dict build, so
sampling all stacks at ~100 Hz costs well under 5% of one core and needs
no signal handlers, no tracing hooks, and no stopping the world.

Output is flamegraph-collapsed format — one line per unique stack,
root;...;leaf count — feedable straight into flamegraph.pl / speedscope
/ inferno.  Sampling is capped (duration <= 60s, hz <= 250, one run at a
time process-wide) so a curious operator cannot turn the profiler into a
self-inflicted load test.
"""

from __future__ import annotations

import os
import sys
import threading
import time

# operator kill-switch: profiling only costs CPU (unlike /debug/faults,
# which mutates behavior and therefore needs opt-IN), so the sampler is
# on by default and this disables it fleet-wide when a deployment wants
# the surface closed
DISABLE_VAR = "SEAWEEDFS_TPU_PROFILER_DISABLED"


def enabled() -> bool:
    return os.environ.get(DISABLE_VAR, "") != "1"


MAX_DURATION_S = 60.0
MAX_HZ = 250
DEFAULT_DURATION_S = 2.0
DEFAULT_HZ = 99  # off the common 100 Hz timer beat, flamegraph folklore

# one sampler per process: two concurrent runs would halve each other's
# accuracy and double the overhead for no information gain
_RUN_LOCK = threading.Lock()


class ProfilerBusy(RuntimeError):
    pass


def _frame_stack(frame, max_depth: int = 64) -> str:
    """root;...;leaf collapsed-stack label for one thread's frame."""
    parts: list[str] = []
    while frame is not None and len(parts) < max_depth:
        code = frame.f_code
        module = frame.f_globals.get("__name__", "?")
        parts.append(f"{module}.{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


def sample_stacks(duration_s: float = DEFAULT_DURATION_S,
                  hz: int = DEFAULT_HZ) -> dict[str, int]:
    """Sample every thread's stack for `duration_s` at `hz`.

    -> {collapsed stack: samples}.  The sampling thread itself is
    excluded.  Raises ProfilerBusy when a run is already in flight and
    ValueError on out-of-range parameters (the endpoint's 400).
    """
    duration_s = float(duration_s)
    hz = int(hz)
    if not 0.0 < duration_s <= MAX_DURATION_S:
        raise ValueError(
            f"duration must be in (0, {MAX_DURATION_S:.0f}] seconds")
    if not 1 <= hz <= MAX_HZ:
        raise ValueError(f"hz must be in [1, {MAX_HZ}]")
    if not _RUN_LOCK.acquire(blocking=False):
        raise ProfilerBusy("a profile run is already in progress")
    try:
        counts: dict[str, int] = {}
        me = threading.get_ident()
        interval = 1.0 / hz
        deadline = time.perf_counter() + duration_s
        next_tick = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now >= deadline:
                return counts
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = _frame_stack(frame)
                if stack:
                    counts[stack] = counts.get(stack, 0) + 1
            # fixed cadence with drop-behind: if a sample ran long, skip
            # the missed ticks instead of bursting to catch up
            next_tick += interval
            now = time.perf_counter()
            if next_tick <= now:
                next_tick = now + interval
            time.sleep(max(0.0, min(next_tick, deadline) - now))
    finally:
        _RUN_LOCK.release()


def collapsed(counts: dict[str, int]) -> str:
    """Flamegraph-collapsed text: `stack count` lines, hottest first."""
    lines = [f"{stack} {n}" for stack, n in
             sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def profile_collapsed(duration_s: float = DEFAULT_DURATION_S,
                      hz: int = DEFAULT_HZ) -> str:
    return collapsed(sample_stacks(duration_s, hz))
