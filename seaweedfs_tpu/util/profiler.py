"""Sampling thread-stack profiler behind /debug/profile.

The previous /debug/profile was a status stub (rusage + thread count) —
useful for "is it big", useless for "where is the time going".  This is
the py-spy idea without the external process: `sys._current_frames()`
returns every thread's current frame for the cost of one dict build, so
sampling all stacks at ~100 Hz costs well under 5% of one core and needs
no signal handlers, no tracing hooks, and no stopping the world.

Output is flamegraph-collapsed format — one line per unique stack,
root;...;leaf count — feedable straight into flamegraph.pl / speedscope
/ inferno.  Sampling is capped (duration <= 60s, hz <= 250, one run at a
time process-wide) so a curious operator cannot turn the profiler into a
self-inflicted load test.

Two consumers share the stack walker:

  * on-demand runs (`sample_stacks`) — an operator asks for N seconds
    at up to 250 Hz, single-flight per process;
  * the flight recorder (`ContinuousProfiler`) — an always-on low-hz
    background sampler keeping a bounded ring of per-window collapsed
    deltas, so when an alert fires the minutes BEFORE it are already on
    record (`/debug/profile/history`).  It deliberately does not take
    `_RUN_LOCK`: at its default 7 Hz it does not disturb an on-demand
    run enough to matter, and pausing history during the one moment an
    operator is actively profiling would blind the recorder exactly
    when things are interesting.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

# operator kill-switch: profiling only costs CPU (unlike /debug/faults,
# which mutates behavior and therefore needs opt-IN), so the sampler is
# on by default and this disables it fleet-wide when a deployment wants
# the surface closed
DISABLE_VAR = "SEAWEEDFS_TPU_PROFILER_DISABLED"


def enabled() -> bool:
    return os.environ.get(DISABLE_VAR, "") != "1"


MAX_DURATION_S = 60.0
MAX_HZ = 250
DEFAULT_DURATION_S = 2.0
DEFAULT_HZ = 99  # off the common 100 Hz timer beat, flamegraph folklore

# one sampler per process: two concurrent runs would halve each other's
# accuracy and double the overhead for no information gain
_RUN_LOCK = threading.Lock()


class ProfilerBusy(RuntimeError):
    pass


def _frame_stack(frame, max_depth: int = 64) -> str:
    """root;...;leaf collapsed-stack label for one thread's frame."""
    parts: list[str] = []
    while frame is not None and len(parts) < max_depth:
        code = frame.f_code
        module = frame.f_globals.get("__name__", "?")
        parts.append(f"{module}.{code.co_name}")
        frame = frame.f_back
    parts.reverse()
    return ";".join(parts)


def sample_stacks(duration_s: float = DEFAULT_DURATION_S,
                  hz: int = DEFAULT_HZ) -> dict[str, int]:
    """Sample every thread's stack for `duration_s` at `hz`.

    -> {collapsed stack: samples}.  The sampling thread itself is
    excluded.  Raises ProfilerBusy when a run is already in flight and
    ValueError on out-of-range parameters (the endpoint's 400).
    """
    duration_s = float(duration_s)
    hz = int(hz)
    if not 0.0 < duration_s <= MAX_DURATION_S:
        raise ValueError(
            f"duration must be in (0, {MAX_DURATION_S:.0f}] seconds")
    if not 1 <= hz <= MAX_HZ:
        raise ValueError(f"hz must be in [1, {MAX_HZ}]")
    if not _RUN_LOCK.acquire(blocking=False):
        raise ProfilerBusy("a profile run is already in progress")
    try:
        counts: dict[str, int] = {}
        me = threading.get_ident()
        interval = 1.0 / hz
        deadline = time.perf_counter() + duration_s
        next_tick = time.perf_counter()
        while True:
            now = time.perf_counter()
            if now >= deadline:
                return counts
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = _frame_stack(frame)
                if stack:
                    counts[stack] = counts.get(stack, 0) + 1
            # fixed cadence with drop-behind: if a sample ran long, skip
            # the missed ticks instead of bursting to catch up
            next_tick += interval
            now = time.perf_counter()
            if next_tick <= now:
                next_tick = now + interval
            time.sleep(max(0.0, min(next_tick, deadline) - now))
    finally:
        _RUN_LOCK.release()


def collapsed(counts: dict[str, int]) -> str:
    """Flamegraph-collapsed text: `stack count` lines, hottest first."""
    lines = [f"{stack} {n}" for stack, n in
             sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def profile_collapsed(duration_s: float = DEFAULT_DURATION_S,
                      hz: int = DEFAULT_HZ) -> str:
    return collapsed(sample_stacks(duration_s, hz))


# -- continuous (flight-recorder) sampler ---------------------------------

# env knobs, read at construction so tests and bench A/B can retune them
# per-instance without a process restart
CONTINUOUS_HZ_VAR = "SEAWEEDFS_TPU_PROFILER_HZ"
CONTINUOUS_WINDOW_VAR = "SEAWEEDFS_TPU_PROFILER_WINDOW_S"
CONTINUOUS_RETAIN_VAR = "SEAWEEDFS_TPU_PROFILER_RETAIN"
DEFAULT_CONTINUOUS_HZ = 7        # low + off the 100 Hz beat; 0 disables
DEFAULT_CONTINUOUS_WINDOW_S = 10.0
DEFAULT_CONTINUOUS_RETAIN = 36   # 36 x 10s = 6 minutes of history
# per-window unique-stack bound: a pathological thread count cannot grow
# a window without limit; overflow collapses into one "(other)" bucket
MAX_WINDOW_STACKS = 512


def _env_float(var: str, default: float) -> float:
    try:
        return float(os.environ.get(var, "") or default)
    except ValueError:
        return default


class ContinuousProfiler:
    """Always-on low-hz sampler with a bounded ring of window deltas.

    Each window is an independent collapsed-stack histogram, so the ring
    reads as a time series of flamegraphs: "what was this process doing
    10s/60s/5min before the page".
    """

    def __init__(self, hz: float | None = None,
                 window_s: float | None = None,
                 retain: int | None = None):
        self.hz = _env_float(CONTINUOUS_HZ_VAR,
                             DEFAULT_CONTINUOUS_HZ) if hz is None else hz
        self.window_s = (_env_float(CONTINUOUS_WINDOW_VAR,
                                    DEFAULT_CONTINUOUS_WINDOW_S)
                         if window_s is None else window_s)
        retain = (int(_env_float(CONTINUOUS_RETAIN_VAR,
                                 DEFAULT_CONTINUOUS_RETAIN))
                  if retain is None else retain)
        self.hz = min(float(self.hz), float(MAX_HZ))
        self.window_s = max(0.05, float(self.window_s))
        self._windows: deque[dict] = deque(maxlen=max(1, retain))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._cur: dict[str, int] = {}
        self._cur_start = time.time()
        self._cur_samples = 0

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> None:
        if self.hz <= 0 or self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="profiler-continuous")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    def _sample_once(self) -> None:
        me = threading.get_ident()
        sampler = self._thread.ident if self._thread else me
        for tid, frame in sys._current_frames().items():
            if tid in (me, sampler):
                continue
            stack = _frame_stack(frame)
            if not stack:
                continue
            if stack in self._cur or len(self._cur) < MAX_WINDOW_STACKS:
                self._cur[stack] = self._cur.get(stack, 0) + 1
            else:
                self._cur["(other)"] = self._cur.get("(other)", 0) + 1
        self._cur_samples += 1

    def _rotate(self, now: float) -> None:
        with self._lock:
            self._windows.append({
                "start": self._cur_start,
                "end": now,
                "samples": self._cur_samples,
                "collapsed": collapsed(self._cur),
            })
            self._cur = {}
            self._cur_start = now
            self._cur_samples = 0

    def _run(self) -> None:
        interval = 1.0 / self.hz
        window_end = time.time() + self.window_s
        while not self._stop.wait(interval):
            self._sample_once()
            now = time.time()
            if now >= window_end:
                self._rotate(now)
                window_end = now + self.window_s

    def history(self) -> dict:
        """JSON doc for /debug/profile/history: closed windows oldest
        first, plus the in-progress window (partial=True) — during an
        incident the current window is the one that matters."""
        with self._lock:
            windows = list(self._windows)
            if self._cur_samples:
                windows.append({
                    "start": self._cur_start,
                    "end": time.time(),
                    "samples": self._cur_samples,
                    "collapsed": collapsed(dict(self._cur)),
                    "partial": True,
                })
        return {
            "hz": self.hz,
            "windowS": self.window_s,
            "retain": self._windows.maxlen,
            "running": self.running,
            "windows": windows,
        }


_CONTINUOUS: ContinuousProfiler | None = None
_CONTINUOUS_LOCK = threading.Lock()


def ensure_continuous() -> ContinuousProfiler | None:
    """Start (or return) the process-wide continuous sampler.

    Idempotent — every server's start() calls it; the first call wins.
    Returns None when the kill-switch is set or hz is tuned to 0."""
    if not enabled():
        return None
    global _CONTINUOUS
    with _CONTINUOUS_LOCK:
        if _CONTINUOUS is None or not _CONTINUOUS.running:
            prof = ContinuousProfiler()
            if prof.hz <= 0:
                return None
            prof.start()
            _CONTINUOUS = prof
        return _CONTINUOUS


def stop_continuous() -> None:
    """Stop and forget the process-wide sampler (bench A/B, tests)."""
    global _CONTINUOUS
    with _CONTINUOUS_LOCK:
        if _CONTINUOUS is not None:
            _CONTINUOUS.stop()
            _CONTINUOUS = None


def continuous_history() -> dict:
    """The /debug/profile/history body, whether or not the sampler runs."""
    with _CONTINUOUS_LOCK:
        prof = _CONTINUOUS
    if prof is None:
        return {
            "hz": _env_float(CONTINUOUS_HZ_VAR, DEFAULT_CONTINUOUS_HZ),
            "windowS": _env_float(CONTINUOUS_WINDOW_VAR,
                                  DEFAULT_CONTINUOUS_WINDOW_S),
            "retain": int(_env_float(CONTINUOUS_RETAIN_VAR,
                                     DEFAULT_CONTINUOUS_RETAIN)),
            "running": False,
            "windows": [],
        }
    return prof.history()
