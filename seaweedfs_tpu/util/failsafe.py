"""Unified fault-tolerance policy for every cross-process call path.

One place defines how the cluster retries, backs off, deadlines and
circuit-breaks — replacing the scattered `time.sleep(0.2*(attempt+1))`,
`sleep(1.747)` and bare fixed timeouts that predated it.  The design
follows the degraded-mode findings of the warehouse-cluster study
(arXiv:1309.0186): recovery traffic dominates exactly when peers fail,
so failure handling must shed load (full-jitter backoff), bound work
(deadlines) and stop hammering dead peers (per-peer breakers) instead of
synchronized linear retries.

Pieces:

  RetryPolicy   — attempts + exponential backoff with FULL jitter
                  (delay ~ U(0, min(cap, base*2^attempt))), AWS-style.
  Deadline      — a total-time budget carried in a contextvar; pb/rpc.py
                  stubs clamp their per-call timeout to the remaining
                  budget so a caller's deadline propagates through every
                  nested rpc hop.
  classify      — maps an exception to (reason, retryable) with
                  idempotency awareness: a connect error never reached
                  the server so even a POST may retry it; a mid-body
                  timeout is retryable only for idempotent ops.
  CircuitBreaker— per-peer closed/open/half-open with a consecutive-
                  failure threshold; breaker_for() is the process-wide
                  registry.
  call          — retry loop over one callable (one peer).
  call_with_failover — retry loop over a rotating peer list (masters,
                  replica locations), breaker-gated.

Everything emits through the PR-1 telemetry layer:

  seaweedfs_retry_total{type,op,reason}        every retried failure
  seaweedfs_circuit_state{peer}                0 closed / 1 open / 2 half-open
  seaweedfs_circuit_transitions_total{peer,to} state changes
"""

from __future__ import annotations

import contextlib
import contextvars
import http.client
import json
import random
import socket
import threading
import time
import urllib.error
from dataclasses import dataclass, field
from typing import Callable, Iterable

from ..stats.metrics import (  # families declared centrally for the lint
    CIRCUIT_STATE,
    CIRCUIT_TRANSITIONS,
    RETRY_COUNTER,
)
from . import glog

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_VALUE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


# ---------------------------------------------------------------------------
# Retry policy + backoff
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """How often and how fast to retry one logical operation."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 5.0
    timeout: float | None = None  # per-attempt timeout hint for callers

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        """Full-jitter backoff for the given 0-based failed attempt.  The
        exponent is clamped so open-ended reconnect loops can call this
        forever without overflowing a float (2.0**1024 raises)."""
        cap = min(self.max_delay,
                  self.base_delay * (2.0 ** min(attempt, 62)))
        return (rng or _rng).uniform(0.0, cap)


# sensible defaults per edge; callers may pass their own
DEFAULT_POLICY = RetryPolicy()
UPLOAD_POLICY = RetryPolicy(max_attempts=3, base_delay=0.1, max_delay=2.0)
DOWNLOAD_POLICY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=1.0)
RPC_POLICY = RetryPolicy(max_attempts=3, base_delay=0.05, max_delay=2.0)
RECONNECT_POLICY = RetryPolicy(max_attempts=1 << 30, base_delay=0.5,
                               max_delay=30.0)

_rng = random.Random()


class Backoff:
    """Stateful jittered backoff for open-ended reconnect loops
    (replicator, keep-connected): next() grows, reset() after success."""

    def __init__(self, policy: RetryPolicy = RECONNECT_POLICY,
                 rng: random.Random | None = None):
        self.policy = policy
        self.attempt = 0
        self._rng = rng or _rng

    def next(self) -> float:
        d = self.policy.delay(self.attempt, self._rng)
        self.attempt += 1
        return d

    def reset(self) -> None:
        self.attempt = 0


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------


class DeadlineExceeded(TimeoutError):
    """The caller's total-time budget ran out before the op completed."""


class Deadline:
    def __init__(self, seconds: float, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.expires_at = clock() + seconds

    def remaining(self) -> float:
        return self.expires_at - self._clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0


_deadline_var: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "seaweedfs_deadline", default=None)


def current_deadline() -> Deadline | None:
    return _deadline_var.get()


@contextlib.contextmanager
def deadline_scope(seconds: float):
    """Install a total-time budget for everything inside the scope.  Nested
    scopes never extend an outer budget — the tighter deadline wins."""
    outer = _deadline_var.get()
    inner = Deadline(seconds)
    if outer is not None and outer.expires_at < inner.expires_at:
        inner = outer
    token = _deadline_var.set(inner)
    try:
        yield inner
    finally:
        _deadline_var.reset(token)


def attempt_timeout(default: float | None) -> float | None:
    """Clamp a per-attempt timeout to the ambient deadline's remainder.

    Raises DeadlineExceeded when the budget is already spent — better to
    fail in the caller than to fire a guaranteed-to-timeout request."""
    dl = _deadline_var.get()
    if dl is None:
        return default
    rem = dl.remaining()
    if rem <= 0.0:
        raise DeadlineExceeded("deadline exceeded before attempt")
    if default is None:
        return rem
    return min(default, rem)


# ---------------------------------------------------------------------------
# Failure classification
# ---------------------------------------------------------------------------


def classify(exc: BaseException, idempotent: bool = True) -> tuple[str, bool]:
    """-> (reason label, retryable?) for one failed attempt.

    Idempotency-aware: a connect-phase failure (refused / unreachable /
    DNS) never delivered the request, so retrying is safe even for
    non-idempotent POSTs.  An HTTP 5xx is an explicit server-side NACK
    before the write was acknowledged — also retry-safe.  A timeout or
    reset mid-exchange is ambiguous (the body may have been applied), so
    only idempotent operations retry it."""
    # unwrap urllib's URLError(reason=<socket error>) envelope
    if isinstance(exc, urllib.error.HTTPError):
        if exc.code >= 500:
            return f"http_{exc.code}", True
        return f"http_{exc.code}", False
    if isinstance(exc, urllib.error.URLError):
        inner = exc.reason
        if isinstance(inner, BaseException):
            return classify(inner, idempotent)
        return "connect", True
    if isinstance(exc, DeadlineExceeded):
        return "deadline", False
    if isinstance(exc, ConnectionRefusedError):
        return "refused", True
    if isinstance(exc, (ConnectionResetError, ConnectionAbortedError,
                        BrokenPipeError)):
        return "reset", idempotent
    if isinstance(exc, (socket.timeout, TimeoutError)):
        return "timeout", idempotent
    if isinstance(exc, socket.gaierror):
        return "dns", True
    if isinstance(exc, http.client.RemoteDisconnected):
        return "reset", idempotent
    if isinstance(exc, http.client.HTTPException):
        return "http_proto", idempotent
    if isinstance(exc, json.JSONDecodeError):
        # a 2xx with a garbled body: the write may have landed
        return "bad_response", False
    try:  # grpc is always present in this image, but keep the probe cheap
        import grpc
    except ImportError:  # pragma: no cover
        grpc = None
    if grpc is not None and isinstance(exc, grpc.RpcError):
        code = exc.code() if callable(getattr(exc, "code", None)) else None
        if code == grpc.StatusCode.UNAVAILABLE:
            return "unavailable", True
        if code == grpc.StatusCode.DEADLINE_EXCEEDED:
            return "timeout", idempotent
        if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
            return "exhausted", True
        if code == grpc.StatusCode.FAILED_PRECONDITION:
            # "not the leader" and friends: peer-specific, rotate/retry
            return "failed_precondition", True
        return f"grpc_{code.name.lower()}" if code else "grpc", False
    if isinstance(exc, OSError):
        return "os_error", idempotent
    return "error", False


def is_connection_refused(exc: BaseException) -> bool:
    """True when the peer actively refused the connection — the signal to
    evict its cached locations (the process is gone, not just slow)."""
    if isinstance(exc, ConnectionRefusedError):
        return True
    if isinstance(exc, urllib.error.URLError) and not isinstance(
            exc, urllib.error.HTTPError):
        return isinstance(exc.reason, ConnectionRefusedError)
    return False


# ---------------------------------------------------------------------------
# Circuit breakers
# ---------------------------------------------------------------------------


class CircuitOpenError(ConnectionError):
    """Fast-failed: the peer's breaker is open (recent consecutive
    failures); no request was sent."""


class CircuitBreaker:
    """Per-peer consecutive-failure breaker.

    closed --(threshold consecutive failures)--> open
    open   --(reset_timeout elapsed)-->           half-open (one probe)
    half-open --success--> closed ; --failure--> open
    """

    def __init__(self, peer: str, failure_threshold: int = 5,
                 reset_timeout: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.peer = peer
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_in_flight = False
        CIRCUIT_STATE.labels(peer).set(0.0)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, to: str) -> None:
        # lock held by caller
        if self._state == to:
            return
        self._state = to
        CIRCUIT_STATE.labels(self.peer).set(_STATE_VALUE[to])
        CIRCUIT_TRANSITIONS.labels(self.peer, to).inc()
        glog.info("circuit %s -> %s trace=%s", self.peer, to,
                  _trace_id() or "-")

    def allow(self) -> bool:
        """May a request go to this peer right now?  An open breaker whose
        reset timeout elapsed flips to half-open and admits ONE probe."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.reset_timeout:
                    self._transition(HALF_OPEN)
                    self._probe_in_flight = True
                    return True
                return False
            # half-open: one probe at a time
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def release_probe(self) -> None:
        """The admitted request was abandoned before it reached the peer
        (caller's deadline spent): free the half-open probe slot without
        judging the peer either way."""
        with self._lock:
            self._probe_in_flight = False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if (self._state == CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._opened_at = self._clock()
                self._transition(OPEN)


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()

# tunables applied to breakers created after the change (tests shrink them)
BREAKER_FAILURE_THRESHOLD = 5
BREAKER_RESET_TIMEOUT = 10.0


def breaker_for(peer: str) -> CircuitBreaker:
    with _breakers_lock:
        br = _breakers.get(peer)
        if br is None:
            br = CircuitBreaker(peer, BREAKER_FAILURE_THRESHOLD,
                                BREAKER_RESET_TIMEOUT)
            _breakers[peer] = br
        return br


def reset_breakers() -> None:
    """Drop all breaker state (tests; also useful after reconfiguration)."""
    with _breakers_lock:
        _breakers.clear()


# ---------------------------------------------------------------------------
# Retry loops
# ---------------------------------------------------------------------------


def _trace_id() -> str | None:
    from ..telemetry import trace

    return trace.current_trace_id()


def _breaker_judges_failure(e: BaseException) -> bool:
    """Whether an exception counts against the peer's circuit breaker.

    An HTTP 4xx is a full answer from a live, healthy peer — a typed
    409 volume-full, a 404 stale location, a 403 auth miss say nothing
    about its availability.  Opening the breaker on them makes ONE full
    volume fail fast every other request to that server for the reset
    window (observed live: a burst of volume-full 409s opened the
    breaker and re-assigned uploads died on "circuit open" instead of
    landing on the server's other volumes).  5xx and transport errors
    still count — that is what the breaker is for."""
    return not (isinstance(e, urllib.error.HTTPError)
                and 400 <= e.code < 500)


def _breaker_record(br, e: BaseException) -> None:
    if _breaker_judges_failure(e):
        br.record_failure()
    else:
        br.record_success()  # the peer answered: it is alive


def _sleep_backoff(policy: RetryPolicy, attempt: int,
                   rng: random.Random | None = None) -> None:
    delay = policy.delay(attempt, rng)
    dl = _deadline_var.get()
    if dl is not None:
        rem = dl.remaining()
        if rem <= 0.0:
            raise DeadlineExceeded("deadline exceeded during backoff")
        delay = min(delay, rem)
    if delay > 0.0:
        time.sleep(delay)


def call(
    fn: Callable[[], object],
    *,
    op: str,
    retry_type: str = "client",
    policy: RetryPolicy = DEFAULT_POLICY,
    peer: str | None = None,
    idempotent: bool = True,
    rng: random.Random | None = None,
):
    """Run fn() under the retry policy against one peer.

    Raises the last exception once attempts/deadline are exhausted or the
    failure is classified non-retryable.  When `peer` is given, the call
    is breaker-gated: an open breaker raises CircuitOpenError without
    attempting, and every outcome feeds the breaker."""
    br = breaker_for(peer) if peer else None
    last: BaseException | None = None
    for attempt in range(max(1, policy.max_attempts)):
        if br is not None and not br.allow():
            raise CircuitOpenError(f"circuit open for {peer}")
        try:
            result = fn()
        except BaseException as e:  # noqa: BLE001 - classified below
            if br is not None:
                if isinstance(e, DeadlineExceeded):
                    # a spent budget says nothing about THIS peer's
                    # health — the request may never have been sent; but
                    # an admitted half-open probe slot must be freed or
                    # the breaker wedges open forever
                    br.release_probe()
                else:
                    _breaker_record(br, e)
            reason, retryable = classify(e, idempotent)
            last = e
            if not retryable or attempt + 1 >= policy.max_attempts:
                raise
            RETRY_COUNTER.labels(retry_type, op, reason).inc()
            glog.info("retry %s.%s attempt=%d reason=%s peer=%s trace=%s",
                      retry_type, op, attempt + 1, reason, peer or "-",
                      _trace_id() or "-")
            _sleep_backoff(policy, attempt, rng)
            continue
        if br is not None:
            br.record_success()
        return result
    raise last  # pragma: no cover - loop always returns or raises


def call_with_failover(
    peers: Iterable[str] | Callable[[int], Iterable[str]],
    fn: Callable[[str], object],
    *,
    op: str,
    retry_type: str = "client",
    policy: RetryPolicy = RPC_POLICY,
    idempotent: bool = True,
    on_peer_failure: Callable[[str, BaseException], None] | None = None,
    peer_key: Callable[[str], str] | None = None,
    rng: random.Random | None = None,
):
    """Try fn(peer) across a peer list with breaker gating and jittered
    backoff between full rounds (policy.max_attempts rounds).

    `peers` may be a callable round -> iterable so the caller can refresh
    the candidate list between rounds (e.g. re-ask the master after every
    cached location failed).  `peer_key` maps a candidate to its breaker
    key (e.g. a fid URL to its host:port) so breaker state aggregates per
    server.  If every peer in a round was skipped by an open breaker, one
    is probed anyway — total lockout must degrade to "slow", never to
    "impossible".

    Unlike call(), a non-retryable failure does NOT abort the rotation:
    one replica answering 404 (stale vid map, missing copy) says nothing
    about the others, so every candidate gets its chance and the LAST
    error surfaces.  Only an exhausted deadline ends the loop early —
    the budget is gone for every remaining peer alike."""
    key = peer_key or (lambda p: p)
    last: BaseException | None = None
    for round_no in range(max(1, policy.max_attempts)):
        candidates = list(peers(round_no) if callable(peers) else peers)
        if not candidates:
            break
        attempted = 0
        for peer in candidates:
            br = breaker_for(key(peer))
            if not br.allow():
                continue
            attempted += 1
            try:
                result = fn(peer)
            except DeadlineExceeded:
                # budget spent: no peer can help; free the probe slot the
                # allow() above may have claimed, judge the peer neither way
                br.release_probe()
                raise
            except BaseException as e:  # noqa: BLE001 - classified below
                _breaker_record(br, e)
                if on_peer_failure is not None:
                    on_peer_failure(peer, e)
                reason, _retryable = classify(e, idempotent)
                last = e
                RETRY_COUNTER.labels(retry_type, op, reason).inc()
                glog.info(
                    "failover %s.%s peer=%s reason=%s round=%d trace=%s",
                    retry_type, op, peer, reason, round_no, _trace_id() or "-")
                continue
            br.record_success()
            return result
        if attempted == 0:
            # every breaker open: force-probe the first candidate so a
            # cluster-wide blip cannot wedge us for reset_timeout
            peer = candidates[0]
            try:
                result = fn(peer)
            except DeadlineExceeded:
                breaker_for(key(peer)).release_probe()
                raise
            except BaseException as e:  # noqa: BLE001
                _breaker_record(breaker_for(key(peer)), e)
                if on_peer_failure is not None:
                    on_peer_failure(peer, e)
                reason, _retryable = classify(e, idempotent)
                last = e
                RETRY_COUNTER.labels(retry_type, op, reason).inc()
            else:
                breaker_for(key(peer)).record_success()
                return result
        if round_no + 1 < policy.max_attempts:
            _sleep_backoff(policy, round_no, rng)
    if last is not None:
        raise last
    raise CircuitOpenError(f"{op}: no peers available")
