"""Shared socket helpers for the framework-native wire clients."""

from __future__ import annotations

import socket


def read_exact(sock: socket.socket, n: int, what: str = "peer") -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError(f"{what} connection closed")
        out += chunk
    return out
