"""In-process Elasticsearch 7 REST subset — the elastic7 store's test
double (same role as FakeRedisServer / FakeEtcdServer: it proves the
client's wire behavior without the external service).

Implements exactly what filer/stores/elastic_store.py sends:
  PUT/GET/DELETE /{index}/_doc/{id}
  POST /{index}/_search   (ParentId term + optional name range, sorted)
  POST /{index}/_delete_by_query  (bool should of term/prefix on dir)
  DELETE /{index}
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_DOC_RE = re.compile(r"^/([^/]+)/_doc/([^/?]+)$")
_SEARCH_RE = re.compile(r"^/([^/]+)/_search$")
_DBQ_RE = re.compile(r"^/([^/]+)/_delete_by_query$")
_INDEX_RE = re.compile(r"^/([^/]+)$")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # noqa: D102 — quiet
        pass

    @property
    def db(self):
        return self.server.indices  # type: ignore[attr-defined]

    @property
    def lock(self):
        return self.server.lock  # type: ignore[attr-defined]

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n else b""
        return json.loads(raw) if raw else {}

    def do_PUT(self):
        m = _DOC_RE.match(self.path)
        if not m:
            return self._json(400, {"error": "bad path"})
        index, doc_id = m.groups()
        doc = self._body()
        with self.lock:
            created = doc_id not in self.db.setdefault(index, {})
            self.db[index][doc_id] = doc
        self._json(201 if created else 200,
                   {"result": "created" if created else "updated"})

    def do_GET(self):
        m = _DOC_RE.match(self.path)
        if not m:
            return self._json(400, {"error": "bad path"})
        index, doc_id = m.groups()
        with self.lock:
            doc = self.db.get(index, {}).get(doc_id)
        if doc is None:
            return self._json(404, {"found": False})
        self._json(200, {"found": True, "_id": doc_id, "_source": doc})

    def do_DELETE(self):
        m = _DOC_RE.match(self.path)
        with self.lock:
            if m:
                index, doc_id = m.groups()
                existed = self.db.get(index, {}).pop(doc_id, None)
                return self._json(
                    200 if existed else 404,
                    {"result": "deleted" if existed else "not_found"})
            m = _INDEX_RE.match(self.path)
            if m:
                self.db.pop(m.group(1), None)
                return self._json(200, {"acknowledged": True})
        self._json(400, {"error": "bad path"})

    def do_POST(self):
        m = _SEARCH_RE.match(self.path)
        if m:
            return self._search(m.group(1), self._body())
        m = _DBQ_RE.match(self.path)
        if m:
            return self._delete_by_query(m.group(1), self._body())
        self._json(400, {"error": "bad path"})

    # -- query evaluation --------------------------------------------------

    @staticmethod
    def _matches(doc: dict, query: dict) -> bool:
        if "term" in query:
            ((field, want),) = query["term"].items()
            return doc.get(field.replace(".keyword", "")) == want
        if "prefix" in query:
            ((field, want),) = query["prefix"].items()
            return str(doc.get(field.replace(".keyword", ""), "")
                       ).startswith(want)
        if "range" in query:
            ((field, conds),) = query["range"].items()
            val = doc.get(field.replace(".keyword", ""))
            if val is None:
                return False
            for op, bound in conds.items():
                if op == "gt" and not val > bound:
                    return False
                if op == "gte" and not val >= bound:
                    return False
                if op == "lt" and not val < bound:
                    return False
                if op == "lte" and not val <= bound:
                    return False
            return True
        if "bool" in query:
            b = query["bool"]
            if not all(_Handler._matches(doc, q)
                       for q in b.get("must", [])):
                return False
            if not all(_Handler._matches(doc, q)
                       for q in b.get("filter", [])):
                return False
            should = b.get("should", [])
            if should and not any(_Handler._matches(doc, q)
                                  for q in should):
                return False
            return True
        return True  # match_all

    def _search(self, index: str, body: dict) -> None:
        query = body.get("query", {})
        size = int(body.get("size", 10))
        with self.lock:
            docs = list(self.db.get(index, {}).items())
        hits = [{"_id": i, "_source": d} for i, d in docs
                if self._matches(d, query)]
        for sort in reversed(body.get("sort", [])):
            ((field, order),) = sort.items() if isinstance(sort, dict) \
                else ((sort, "asc"),)
            if isinstance(order, dict):
                order = order.get("order", "asc")
            hits.sort(key=lambda h: h["_source"].get(
                field.replace(".keyword", ""), ""),
                reverse=(order == "desc"))
        hits = hits[:size]
        self._json(200, {"hits": {"total": {"value": len(hits)},
                                  "hits": hits}})

    def _delete_by_query(self, index: str, body: dict) -> None:
        query = body.get("query", {})
        with self.lock:
            idx = self.db.get(index, {})
            victims = [i for i, d in idx.items()
                       if self._matches(d, query)]
            for i in victims:
                del idx[i]
        self._json(200, {"deleted": len(victims)})


class FakeElasticServer:
    def __init__(self, port: int = 0):
        self.port = port
        self._srv: ThreadingHTTPServer | None = None

    def start(self) -> None:
        self._srv = ThreadingHTTPServer(("127.0.0.1", self.port), _Handler)
        self._srv.indices = {}  # type: ignore[attr-defined]
        self._srv.lock = threading.Lock()  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self) -> None:
        if self._srv is not None:
            self._srv.shutdown()
            self._srv.server_close()
            self._srv = None
