"""Framework-native etcd v3 client (gRPC KV plane) + in-process fake.

The reference gates two components on etcd: the sequencer
(weed/sequence/etcd_sequencer.go:26) and a filer store
(weed/filer/etcd/etcd_store.go:23).  This image ships no etcd server or
client library, so — like the RESP client written for the redis store —
the framework speaks the wire protocol itself: `EtcdClient` drives the
real etcdserverpb.KV service (names + field numbers match stock etcd;
see pb/etcd.proto), and `FakeEtcdServer` implements the same four rpcs
in-process for tests and offline development.
"""

from __future__ import annotations

import threading

from ..pb import etcd_pb2
from ..pb import rpc as rpclib


def prefix_range_end(prefix: bytes) -> bytes:
    """clientv3.WithPrefix's range_end: prefix with its last byte +1
    (etcd-io/etcd clientv3/op.go getPrefix)."""
    end = bytearray(prefix)
    for i in reversed(range(len(end))):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[: i + 1])
    return b"\0"  # all 0xff: from-key range


class EtcdClient:
    """Minimal KV surface: get/put/delete/prefix ops + one CAS txn."""

    def __init__(self, address: str = "127.0.0.1:2379",
                 timeout: float = 10.0):
        self.address = address
        self.timeout = timeout

    def _kv(self):
        return rpclib.etcd_kv_stub(self.address, timeout=self.timeout)

    def get(self, key: bytes) -> bytes | None:
        resp = self._kv().Range(etcd_pb2.RangeRequest(key=key))
        return resp.kvs[0].value if resp.kvs else None

    def put(self, key: bytes, value: bytes) -> None:
        self._kv().Put(etcd_pb2.PutRequest(key=key, value=value))

    def delete(self, key: bytes) -> int:
        return self._kv().DeleteRange(
            etcd_pb2.DeleteRangeRequest(key=key)).deleted

    def delete_prefix(self, prefix: bytes) -> int:
        return self._kv().DeleteRange(etcd_pb2.DeleteRangeRequest(
            key=prefix, range_end=prefix_range_end(prefix))).deleted

    def range_prefix(self, prefix: bytes, start: bytes = b"",
                     limit: int = 0) -> list[tuple[bytes, bytes]]:
        """Ascending (key, value) pairs under prefix, optionally starting
        at `start` (>= start, still bounded by the prefix's range end)."""
        resp = self._kv().Range(etcd_pb2.RangeRequest(
            key=start or prefix,
            range_end=prefix_range_end(prefix),
            limit=limit,
            sort_order=1,  # ASCEND
        ))
        return [(kv.key, kv.value) for kv in resp.kvs]

    def cas(self, key: bytes, expect: bytes | None,
            new_value: bytes) -> bool:
        """Compare-and-swap on VALUE; expect=None means 'key absent'
        (compared via create_revision == 0, the etcd idiom)."""
        if expect is None:
            cmp = etcd_pb2.Compare(
                result=0, target=1, key=key, create_revision=0)
        else:
            cmp = etcd_pb2.Compare(
                result=0, target=3, key=key, value=expect)
        resp = self._kv().Txn(etcd_pb2.TxnRequest(
            compare=[cmp],
            success=[etcd_pb2.RequestOp(
                request_put=etcd_pb2.PutRequest(key=key, value=new_value))],
        ))
        return resp.succeeded


class FakeEtcdServer:
    """In-process etcdserverpb.KV over a dict — the test double proving
    the client's wire behavior (same role as util.resp.FakeRedisServer)."""

    def __init__(self, port: int = 0):
        self._lock = threading.Lock()
        self._kv: dict[bytes, tuple[bytes, int, int]] = {}  # v, create, mod
        self._rev = 0
        self._server = None
        self.port = port

    # -- rpc impls ---------------------------------------------------------

    def _select(self, key: bytes, range_end: bytes) -> list[bytes]:
        if not range_end:
            return [key] if key in self._kv else []
        if range_end == b"\0":
            return sorted(k for k in self._kv if k >= key)
        return sorted(k for k in self._kv if key <= k < range_end)

    def _header(self):
        return etcd_pb2.ResponseHeader(revision=self._rev)

    def Range(self, request, context=None):
        with self._lock:
            keys = self._select(request.key, request.range_end)
            if request.sort_order == 2:
                keys.reverse()
            more = bool(request.limit) and len(keys) > request.limit
            if request.limit:
                keys = keys[: request.limit]
            resp = etcd_pb2.RangeResponse(
                header=self._header(), more=more, count=len(keys))
            if not request.count_only:
                for k in keys:
                    v, cr, mr = self._kv[k]
                    resp.kvs.add(key=k, value=b"" if request.keys_only
                                 else v, create_revision=cr,
                                 mod_revision=mr, version=1)
            return resp

    def Put(self, request, context=None):
        with self._lock:
            return self._put_locked(request)

    def _put_locked(self, request):
        self._rev += 1
        old = self._kv.get(request.key)
        create = old[1] if old else self._rev
        self._kv[request.key] = (bytes(request.value), create, self._rev)
        return etcd_pb2.PutResponse(header=self._header())

    def DeleteRange(self, request, context=None):
        with self._lock:
            keys = self._select(request.key, request.range_end)
            if keys:
                self._rev += 1
            for k in keys:
                del self._kv[k]
            return etcd_pb2.DeleteRangeResponse(
                header=self._header(), deleted=len(keys))

    def Txn(self, request, context=None):
        with self._lock:
            ok = all(self._compare(c) for c in request.compare)
            ops = request.success if ok else request.failure
            resp = etcd_pb2.TxnResponse(header=self._header(), succeeded=ok)
            for op in ops:
                kind = op.WhichOneof("request")
                if kind == "request_put":
                    r = self._put_locked(op.request_put)
                    resp.responses.add(response_put=r)
                elif kind == "request_range":
                    pass  # not needed by the framework's callers
            return resp

    def _compare(self, c) -> bool:
        entry = self._kv.get(c.key)
        if c.target == 1:  # CREATE revision
            actual = entry[1] if entry else 0
            want = c.create_revision
        elif c.target == 2:  # MOD revision
            actual = entry[2] if entry else 0
            want = c.mod_revision
        elif c.target == 3:  # VALUE (absent compares unequal to any value)
            actual = entry[0] if entry else None
            want = bytes(c.value)
        else:  # VERSION
            actual = 1 if entry else 0
            want = c.version
        if c.result == 0:
            return actual == want
        if c.result == 3:
            return actual != want
        if c.result == 1:
            return actual is not None and actual > want
        return actual is not None and actual < want

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self.port == 0:
            import socket

            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                self.port = s.getsockname()[1]
        self._server = rpclib.serve(
            [(rpclib.ETCD_KV, self)], self.port, host="127.0.0.1")

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=0.2)
            self._server = None
