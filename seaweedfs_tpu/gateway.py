"""Generic REST gateway: one stable front for blobs, files, and topics.

Reference: weed/command/gateway.go + weed/server/gateway_server.go —
  POST   /blobs/            -> assign + upload, returns the chunk (file) id
  DELETE /blobs/<fid>       -> delete the chunk wherever it lives
  POST   /files/<path>      -> save bytes at the filer path
  DELETE /files/<path>      -> delete the filer path
  POST   /topics/<ns>/<t>   -> append a message to the topic log
Masters are picked round-robin per request.  Filer traffic routes
through the fleet's consistent-hash ring (filer/fleet): with an explicit
``-filer`` list the ring is static; without one, membership is
discovered live from the master's filer registrations, so the gateway is
fully stateless and a filer death re-routes its prefixes to the ring
successor.  The reference left /files and /topics as empty stubs
(gateway_server.go:95-103); here they are functional: files proxy to the
filer HTTP plane, topics append to the filer-backed topic log the
message broker reads.
"""

from __future__ import annotations

import itertools
import json
import threading
import urllib.error
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from .util.httpd import FrameworkHTTPServer

from .filer.fleet import FleetRouter
from .util import connpool, glog


class GatewayServer:
    def __init__(self, masters: list[str], filers: list[str] | None = None,
                 port: int = 5647):
        if not masters:
            raise ValueError("gateway needs at least one master")
        self.port = port
        self._masters = itertools.cycle(masters)
        # static filer list pins the ring; otherwise discover members
        # from the master's KeepConnected filer registrations
        self.router = FleetRouter(
            masters=None if filers else masters,
            filers=filers or None)
        self._httpd: ThreadingHTTPServer | None = None

    def master(self) -> str:
        return next(self._masters)

    def filer_candidates(self, path: str) -> list[str]:
        """Ring-ordered filer addresses for a /files or /topics path."""
        try:
            return self.router.candidates(path)
        except LookupError:
            raise LookupError("no filers configured or discovered")

    def start(self) -> None:
        handler = type("BoundGatewayHandler", (GatewayHandler,),
                       {"gw": self})
        self._httpd = FrameworkHTTPServer(("0.0.0.0", self.port), handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        glog.info("gateway started port=%d", self.port)

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


class GatewayHandler(BaseHTTPRequestHandler):
    gw: GatewayServer
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send_json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        from .util.http_util import read_chunked_body

        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            return read_chunked_body(self.rfile)
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    # -- verbs ---------------------------------------------------------------

    def do_POST(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        query = self.path.partition("?")[2]
        try:
            if path.startswith("/blobs"):
                return self._post_blob()
            if path.startswith("/files/"):
                return self._proxy_filer("PUT", path[len("/files"):],
                                         query)
            if path.startswith("/topics/"):
                return self._post_topic(path[len("/topics/"):])
        except urllib.error.HTTPError as e:
            return self._send_json(e.code, {"error": e.reason})
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    do_PUT = do_POST

    def do_DELETE(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        query = self.path.partition("?")[2]
        try:
            if path.startswith("/blobs/"):
                return self._delete_blob(path[len("/blobs/"):])
            if path.startswith("/files/"):
                return self._proxy_filer("DELETE", path[len("/files"):],
                                         query)
        except urllib.error.HTTPError as e:
            return self._send_json(e.code, {"error": e.reason})
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    def do_GET(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        if path in ("/status", "/healthz"):
            return self._send_json(200, {"gateway": "ok"})
        try:
            if path.startswith("/files/"):
                return self._proxy_filer("GET", path[len("/files"):],
                                         self.path.partition("?")[2])
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    # -- blobs ---------------------------------------------------------------

    def _post_blob(self) -> None:
        from .operation.upload import upload_data

        data = self._body()
        master = self.gw.master()
        with connpool.request(
                "GET", f"http://{master}/dir/assign", timeout=30) as r:
            a = json.loads(r.read())
        if a.get("error"):
            return self._send_json(500, {"error": a["error"]})
        # operation.upload_data: random boundary (payloads containing a
        # fixed boundary string would truncate), jwt, retries
        up = upload_data(f"http://{a['url']}/{a['fid']}", data,
                         filename="blob", jwt=a.get("auth", ""))
        self._send_json(201, {"fid": a["fid"],
                              "url": f"{a['url']}/{a['fid']}",
                              "size": up.size or len(data)})

    def _lookup_locations(self, vid: int):
        from .pb import master_pb2

        master = self.gw.master()
        with connpool.request(
                "GET", f"http://{master}/dir/lookup?volumeId={vid}",
                timeout=30) as r:
            locations = json.loads(r.read()).get("locations", [])
        return [master_pb2.Location(url=loc["url"],
                                    public_url=loc.get("publicUrl", ""))
                for loc in locations]

    def _delete_blob(self, fid: str) -> None:
        from .operation.delete import delete_file_id

        ok = delete_file_id(self._lookup_locations, fid)
        if ok:
            self._send_json(202, {"fid": fid, "deleted": True})
        else:
            self._send_json(404, {"fid": fid, "deleted": False})

    # -- files (filer proxy) -------------------------------------------------

    def _proxy_filer(self, method: str, path: str,
                     query: str = "") -> None:
        data = self._body() if method == "PUT" else None
        qs = f"?{query}" if query else ""
        headers = ({"Content-Type": self.headers.get("Content-Type")
                    or "application/octet-stream"} if data else {})
        last: Exception | None = None
        for i, filer in enumerate(self.gw.filer_candidates(path)[:3]):
            try:
                with connpool.request(
                        method,
                        f"http://{filer}{urllib.parse.quote(path)}{qs}",
                        body=data, headers=headers, timeout=120) as r:
                    body = r.read()
                    self.gw.router.note_route("ok" if i == 0
                                              else "failover")
                    self.send_response(r.status)
                    ct = r.headers.get("Content-Type", "application/json")
                    self.send_header("Content-Type", ct)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
            except urllib.error.HTTPError as e:
                # a real filer answer (404, 403 quota, 503 slowdown):
                # relay it — only transport failures fail over
                self.gw.router.note_route("ok" if i == 0 else "failover")
                return self._send_json(e.code, {"error": str(e.reason)})
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e
                self.gw.router.note_failure(filer)
                continue
        self.gw.router.note_route("error")
        self._send_json(503, {"error": f"no filer shard reachable: {last}"})

    # -- topics (append to the broker's filer-backed log) --------------------

    def _post_topic(self, topic_path: str) -> None:
        data = self._body()
        filer = self.gw.filer_candidates(f"/topics/{topic_path}")[0]
        url = (f"http://{filer}/topics/{urllib.parse.quote(topic_path)}"
               f"/messages.log?op=append")
        with connpool.request(
                "POST", url, body=data,
                headers={"Content-Type": "application/octet-stream"},
                timeout=60) as r:
            self._send_json(r.status, json.loads(r.read() or b"{}"))
