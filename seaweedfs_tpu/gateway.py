"""Generic REST gateway: one stable front for blobs, files, and topics.

Reference: weed/command/gateway.go + weed/server/gateway_server.go —
  POST   /blobs/            -> assign + upload, returns the chunk (file) id
  DELETE /blobs/<fid>       -> delete the chunk wherever it lives
  POST   /files/<path>      -> save bytes at the filer path
  DELETE /files/<path>      -> delete the filer path
  POST   /topics/<ns>/<t>   -> append a message to the topic log
Masters and filers are picked round-robin per request.  The reference
left /files and /topics as empty stubs (gateway_server.go:95-103); here
they are functional: files proxy to the filer HTTP plane, topics append
to the filer-backed topic log the message broker reads.
"""

from __future__ import annotations

import itertools
import json
import threading
import urllib.error
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from .util.httpd import FrameworkHTTPServer

from .util import connpool, glog


class GatewayServer:
    def __init__(self, masters: list[str], filers: list[str] | None = None,
                 port: int = 5647):
        if not masters:
            raise ValueError("gateway needs at least one master")
        self.port = port
        self._masters = itertools.cycle(masters)
        self._filers = itertools.cycle(filers) if filers else None
        self._httpd: ThreadingHTTPServer | None = None

    def master(self) -> str:
        return next(self._masters)

    def filer(self) -> str:
        if self._filers is None:
            raise LookupError("no filers configured")
        return next(self._filers)

    def start(self) -> None:
        handler = type("BoundGatewayHandler", (GatewayHandler,),
                       {"gw": self})
        self._httpd = FrameworkHTTPServer(("0.0.0.0", self.port), handler)
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        glog.info("gateway started port=%d", self.port)

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


class GatewayHandler(BaseHTTPRequestHandler):
    gw: GatewayServer
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _send_json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> bytes:
        from .util.http_util import read_chunked_body

        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            return read_chunked_body(self.rfile)
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    # -- verbs ---------------------------------------------------------------

    def do_POST(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        query = self.path.partition("?")[2]
        try:
            if path.startswith("/blobs"):
                return self._post_blob()
            if path.startswith("/files/"):
                return self._proxy_filer("PUT", path[len("/files"):],
                                         query)
            if path.startswith("/topics/"):
                return self._post_topic(path[len("/topics/"):])
        except urllib.error.HTTPError as e:
            return self._send_json(e.code, {"error": e.reason})
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    do_PUT = do_POST

    def do_DELETE(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        query = self.path.partition("?")[2]
        try:
            if path.startswith("/blobs/"):
                return self._delete_blob(path[len("/blobs/"):])
            if path.startswith("/files/"):
                return self._proxy_filer("DELETE", path[len("/files"):],
                                         query)
        except urllib.error.HTTPError as e:
            return self._send_json(e.code, {"error": e.reason})
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    def do_GET(self):
        path = urllib.parse.unquote(self.path.partition("?")[0])
        if path in ("/status", "/healthz"):
            return self._send_json(200, {"gateway": "ok"})
        try:
            if path.startswith("/files/"):
                return self._proxy_filer("GET", path[len("/files"):],
                                         self.path.partition("?")[2])
        except Exception as e:  # noqa: BLE001
            return self._send_json(500, {"error": str(e)})
        self._send_json(404, {"error": "unknown route"})

    # -- blobs ---------------------------------------------------------------

    def _post_blob(self) -> None:
        from .operation.upload import upload_data

        data = self._body()
        master = self.gw.master()
        with connpool.request(
                "GET", f"http://{master}/dir/assign", timeout=30) as r:
            a = json.loads(r.read())
        if a.get("error"):
            return self._send_json(500, {"error": a["error"]})
        # operation.upload_data: random boundary (payloads containing a
        # fixed boundary string would truncate), jwt, retries
        up = upload_data(f"http://{a['url']}/{a['fid']}", data,
                         filename="blob", jwt=a.get("auth", ""))
        self._send_json(201, {"fid": a["fid"],
                              "url": f"{a['url']}/{a['fid']}",
                              "size": up.size or len(data)})

    def _lookup_locations(self, vid: int):
        from .pb import master_pb2

        master = self.gw.master()
        with connpool.request(
                "GET", f"http://{master}/dir/lookup?volumeId={vid}",
                timeout=30) as r:
            locations = json.loads(r.read()).get("locations", [])
        return [master_pb2.Location(url=loc["url"],
                                    public_url=loc.get("publicUrl", ""))
                for loc in locations]

    def _delete_blob(self, fid: str) -> None:
        from .operation.delete import delete_file_id

        ok = delete_file_id(self._lookup_locations, fid)
        if ok:
            self._send_json(202, {"fid": fid, "deleted": True})
        else:
            self._send_json(404, {"fid": fid, "deleted": False})

    # -- files (filer proxy) -------------------------------------------------

    def _proxy_filer(self, method: str, path: str,
                     query: str = "") -> None:
        filer = self.gw.filer()
        data = self._body() if method == "PUT" else None
        qs = f"?{query}" if query else ""
        headers = ({"Content-Type": self.headers.get("Content-Type")
                    or "application/octet-stream"} if data else {})
        try:
            with connpool.request(
                    method, f"http://{filer}{urllib.parse.quote(path)}{qs}",
                    body=data, headers=headers, timeout=120) as r:
                body = r.read()
                self.send_response(r.status)
                ct = r.headers.get("Content-Type", "application/json")
                self.send_header("Content-Type", ct)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
        except urllib.error.HTTPError as e:
            self._send_json(e.code, {"error": str(e.reason)})

    # -- topics (append to the broker's filer-backed log) --------------------

    def _post_topic(self, topic_path: str) -> None:
        data = self._body()
        filer = self.gw.filer()
        url = (f"http://{filer}/topics/{urllib.parse.quote(topic_path)}"
               f"/messages.log?op=append")
        with connpool.request(
                "POST", url, body=data,
                headers={"Content-Type": "application/octet-stream"},
                timeout=60) as r:
            self._send_json(r.status, json.loads(r.read() or b"{}"))
