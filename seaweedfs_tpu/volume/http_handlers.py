"""Volume-server HTTP data path: POST/GET/DELETE `/<vid>,<fid>`.

Reference: weed/server/volume_server_handlers_{read,write}.go — clients
upload directly to volume servers after a master Assign; reads fall back to
EC volumes transparently; replicated writes fan out to peers with
`?type=replicate`.
"""

from __future__ import annotations

import json
import os
import select
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler
from ..util.httpd import (
    BufferedResponseMixin,
    make_http_server,
    shield_handler,
)

from .. import images
from ..security.jwt import token_from_header, verify_write_jwt
from ..telemetry import hotkeys, http_request, serve_debug_http
from ..storage.file_id import FileId
from ..storage.disk_health import DiskFailingError, DiskFullError
from ..storage.needle import (
    FLAG_HAS_MIME,
    FLAG_HAS_NAME,
    CorruptNeedleError,
    Needle,
)
from ..stats.metrics import (
    SENDFILE_BYTES,
    SENDFILE_FALLBACK,
    VOLUME_FULL_REJECT,
)
from ..util import faultpoint


def _sendfile_enabled() -> bool:
    return os.environ.get(
        "SEAWEEDFS_TPU_SENDFILE", "1").strip().lower() not in (
        "0", "off", "false", "none")

# chaos points on the public data path; ctx is this server's host:port so
# one server out of several in-process can be targeted via &match=
FP_GET = faultpoint.register("volume.http.get")
FP_POST = faultpoint.register("volume.http.post")


class VolumeHttpHandler(BufferedResponseMixin, BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "seaweedfs-tpu-volume"

    # injected by serve():
    volume_server = None

    def log_message(self, fmt, *args):  # quiet
        pass

    @property
    def store(self):
        return self.volume_server.store

    def handle_one_request(self):
        # IP whitelist guard (security/guard.go:43)
        guard = self.volume_server.guard
        if guard.networks and not guard.allows(self.client_address[0]):
            try:
                self.raw_requestline = self.rfile.readline(65537)
                if self.raw_requestline and self.parse_request():
                    self._send_json(403, {"error": "ip not in whitelist"})
            except Exception:
                pass
            self.close_connection = True
            return
        super().handle_one_request()

    def _check_write_jwt(self, fid_str: str) -> bool:
        """JWT write-token verification when the cluster is keyed
        (security/jwt.go ValidateJwt)."""
        key = self.volume_server.jwt_signing_key
        if not key:
            return True
        token = token_from_header(self.headers.get("Authorization"))
        return verify_write_jwt(key, token, fid_str)

    def _send(self, code: int, body: bytes = b"", content_type: str = "application/json", extra: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _send_json(self, code: int, obj: dict):
        self._send(code, json.dumps(obj).encode(), "application/json")

    # -- read -------------------------------------------------------------

    def do_GET(self):
        with http_request(self, "volumeServer", "get"):
            self._do_get()

    def _do_get(self):
        path = urllib.parse.urlparse(self.path)
        if path.path in ("/status", "/healthz"):
            return self._send_json(200, {"Version": "seaweedfs-tpu", **self.store.status()})
        if serve_debug_http(self, path.path):
            return
        if path.path == "/debug/scrub":
            return self._send_json(200, self.volume_server.scrubber.status())
        if path.path == "/debug/canary/ec":
            # black-box degraded-read probe: read a live needle with one
            # locally held shard forced through the reconstruct path, CRC
            # (= byte identity) checked.  The master's canary prober
            # drives this so "EC decode broken" pages before a real
            # shard loss discovers it.
            q = urllib.parse.parse_qs(path.query)
            try:
                vid = int(q.get("volume", [""])[0])
                drop = q.get("shard", [""])[0]
                drop_shard = int(drop) if drop else None
            except ValueError:
                return self._send_json(
                    400, {"error": "volume=<int> required; shard=<int>"})
            ev = self.store.find_ec_volume(vid)
            if ev is None:
                return self._send_json(
                    404, {"ok": False,
                          "error": f"ec volume {vid} not here"})
            t0 = time.perf_counter()
            try:
                res = ev.canary_read(drop_shard=drop_shard)
            except KeyError as e:
                # no live needle (empty or fully tombstoned volume):
                # nothing to probe is not a serving failure
                return self._send_json(
                    200, {"ok": False, "empty": True,
                          "error": str(e)[:300]})
            except Exception as e:  # noqa: BLE001 — probe answer, not a crash
                return self._send_json(
                    500, {"ok": False, "error": str(e)[:300]})
            return self._send_json(200, {
                "ok": True,
                "reconstructMs": round(
                    (time.perf_counter() - t0) * 1e3, 3),
                **res,
            })
        if path.path in ("/ui", "/ui/", "/ui/index.html"):
            from ..util.ui import render_status_page

            page = render_status_page(
                f"seaweedfs-tpu volume {self.volume_server.ip}:"
                f"{self.volume_server.port}",
                {"Status": self.store.status()})
            return self._send(200, page, "text/html")
        try:
            fid = FileId.parse(path.path.lstrip("/"))
        except ValueError:
            return self._send_json(404, {"error": "invalid file id"})
        hotkeys.record("needle", str(fid))
        if (
            self.store.find_volume(fid.volume_id) is None
            and self.store.find_ec_volume(fid.volume_id) is None
        ):
            # not local: redirect to a server that has it (ReadRedirect)
            target = self.volume_server.lookup_volume_url(fid.volume_id)
            if target and target != f"{self.volume_server.ip}:{self.volume_server.port}":
                return self._send(
                    302, b"", "text/plain",
                    {"Location": f"http://{target}{self.path}"},
                )
            return self._send_json(404, {"error": f"volume {fid.volume_id} not found"})
        try:
            me = f"{self.volume_server.ip}:{self.volume_server.port}"
            faultpoint.inject(FP_GET, ctx=me)
            if self._maybe_sendfile(fid, path):
                return
            n = self.store.read_needle(fid.volume_id, fid.key)
        except KeyError:
            return self._send_json(404, {"error": "not found"})
        except CorruptNeedleError as e:
            # quarantined by the store; a 5xx is the retryable NACK the
            # filer's _download_failover rotates on, so the client's read
            # lands on a healthy replica while repair runs in background
            return self._send_json(
                500, {"error": f"needle corrupt, retry a replica: {e}"})
        except IOError as e:
            return self._send_json(500, {"error": str(e)})
        if n.cookie != fid.cookie:
            return self._send_json(404, {"error": "cookie mismatch"})
        mime = n.mime.decode() if n.has(FLAG_HAS_MIME) and n.mime else "application/octet-stream"
        data = n.data
        # image GETs: EXIF orientation fix + ?width/?height/?mode resize
        # on read (volume_server_handlers_read.go -> images/resizing.go)
        ext = ""
        name = n.name.decode(errors="replace") if n.name else path.path
        if "." in name:
            ext = "." + name.rsplit(".", 1)[1].lower()
        if images.is_image(ext, mime):
            q = urllib.parse.parse_qs(path.query)
            data = images.fix_orientation(bytes(data))
            try:
                w = int(q.get("width", ["0"])[0] or 0)
                h = int(q.get("height", ["0"])[0] or 0)
            except ValueError:
                return self._send_json(400, {"error": "bad width/height"})
            if w or h:
                data, _, _ = images.resized(
                    bytes(data), ext or "." + mime.rpartition("/")[2],
                    w, h, q.get("mode", [""])[0])
        rng = self.headers.get("Range")
        extra = {
            "Etag": f'"{n.checksum:x}"',
            "Accept-Ranges": "bytes",
        }
        if rng and rng.startswith("bytes="):
            try:
                start_s, end_s = rng[len("bytes="):].split("-", 1)
                if not start_s:
                    # suffix range (RFC 7233): bytes=-N means the last N bytes
                    start = max(0, len(data) - int(end_s))
                    end = len(data) - 1
                else:
                    start = int(start_s)
                    end = int(end_s) if end_s else len(data) - 1
                end = min(end, len(data) - 1)
                if start > end:
                    raise ValueError
                extra["Content-Range"] = f"bytes {start}-{end}/{len(data)}"
                return self._send(206, data[start : end + 1], mime, extra)
            except ValueError:
                return self._send_json(416, {"error": "bad range"})
        self._send(200, data, mime, extra)

    # -- zero-copy read path ----------------------------------------------

    def _maybe_sendfile(self, fid, path) -> bool:
        """Whole-needle GETs serve disk→socket via os.sendfile: the
        payload bytes never enter userspace.  Anything that must touch
        the bytes (Range math, image transforms) or that has them in
        memory already (needle cache) declines and falls back to the
        ordinary read path.  -> True when the response was fully
        handled here."""
        if not _sendfile_enabled():
            SENDFILE_FALLBACK.labels("disabled").inc()
            return False
        if self.headers.get("Range"):
            SENDFILE_FALLBACK.labels("range").inc()
            return False
        ext, reason = self.store.needle_extent(fid.volume_id, fid.key)
        if ext is None:
            SENDFILE_FALLBACK.labels(reason or "error").inc()
            return False
        with ext:
            n = ext.needle
            if n.cookie != fid.cookie:
                self._send_json(404, {"error": "cookie mismatch"})
                return True
            mime = (n.mime.decode() if n.has(FLAG_HAS_MIME) and n.mime
                    else "application/octet-stream")
            name = n.name.decode(errors="replace") if n.name else path.path
            file_ext = ("." + name.rsplit(".", 1)[1].lower()
                        if "." in name else "")
            if images.is_image(file_ext, mime):
                # the GET pipeline re-orients/resizes images in
                # userspace; zero-copy would skip it
                SENDFILE_FALLBACK.labels("transform").inc()
                return False
            self.send_response(200)
            self.send_header("Content-Type", mime)
            self.send_header("Content-Length", str(ext.data_len))
            self.send_header("Etag", f'"{n.checksum:x}"')
            self.send_header("Accept-Ranges", "bytes")
            self.end_headers()
            self._stream_extent(ext)
        return True

    def _stream_extent(self, ext) -> None:
        """Ship ext's byte range after the headers: sendfile first, a
        pread→write loop if the very first sendfile call is refused
        (odd socket type); a failure after any payload byte went out
        can only close the connection — the stream is torn."""
        try:
            self.wfile.flush()  # headers must precede the payload
        except OSError:
            self.close_connection = True
            return
        sock = self.connection
        offset, remaining = ext.data_offset, ext.data_len
        sent_any = False
        try:
            while remaining > 0:
                try:
                    sent = os.sendfile(
                        sock.fileno(), ext.fd, offset, remaining)
                except BlockingIOError:
                    # the socket send buffer is full (the fd is
                    # non-blocking under a socket timeout): wait until
                    # writable, bounded by the same timeout
                    r = select.select(
                        [], [sock], [], sock.gettimeout() or 60.0)
                    if not r[1]:
                        raise OSError(110, "sendfile stalled") from None
                    continue
                if sent == 0:
                    raise OSError(5, "sendfile returned 0")
                sent_any = True
                offset += sent
                remaining -= sent
            SENDFILE_BYTES.inc(ext.data_len)
        except (OSError, AttributeError):
            if sent_any:
                self.close_connection = True
                return
            SENDFILE_FALLBACK.labels("error").inc()
            try:
                while remaining > 0:
                    chunk = os.pread(
                        ext.fd, min(remaining, 1 << 18), offset)
                    if not chunk:
                        raise OSError(5, "short extent read")
                    self.wfile.write(chunk)
                    offset += len(chunk)
                    remaining -= len(chunk)
                self.wfile.flush()
            except OSError:
                self.close_connection = True

    def do_HEAD(self):
        """HEAD answers from needle metadata alone: no EXIF re-orientation,
        no resize — the GET pipeline ran the full image transform only to
        throw the body away.  Content-Length reflects the stored bytes
        (a transformed GET body may differ; metadata-accurate beats
        paying the transform per HEAD)."""
        with http_request(self, "volumeServer", "get"):
            self._do_head()

    def _do_head(self):
        path = urllib.parse.urlparse(self.path)
        try:
            fid = FileId.parse(path.path.lstrip("/"))
        except ValueError:
            # non-fid paths (/status, /ui, debug): same answers as GET,
            # minus the body (_send skips it for HEAD)
            return self._do_get()
        if (
            self.store.find_volume(fid.volume_id) is None
            and self.store.find_ec_volume(fid.volume_id) is None
        ):
            target = self.volume_server.lookup_volume_url(fid.volume_id)
            if target and target != f"{self.volume_server.ip}:{self.volume_server.port}":
                return self._send(
                    302, b"", "text/plain",
                    {"Location": f"http://{target}{self.path}"},
                )
            return self._send_json(404, {"error": f"volume {fid.volume_id} not found"})
        try:
            n = self.store.read_needle(fid.volume_id, fid.key)
        except KeyError:
            return self._send_json(404, {"error": "not found"})
        except CorruptNeedleError as e:
            return self._send_json(
                500, {"error": f"needle corrupt, retry a replica: {e}"})
        except IOError as e:
            return self._send_json(500, {"error": str(e)})
        if n.cookie != fid.cookie:
            return self._send_json(404, {"error": "cookie mismatch"})
        mime = n.mime.decode() if n.has(FLAG_HAS_MIME) and n.mime else "application/octet-stream"
        extra = {
            "Etag": f'"{n.checksum:x}"',
            "Accept-Ranges": "bytes",
        }
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            # range semantics preserved (206 + Content-Range against the
            # stored length) — only the image transforms are skipped
            total = len(n.data)
            try:
                start_s, end_s = rng[len("bytes="):].split("-", 1)
                if not start_s:
                    start = max(0, total - int(end_s))
                    end = total - 1
                else:
                    start = int(start_s)
                    end = int(end_s) if end_s else total - 1
                end = min(end, total - 1)
                if start > end:
                    raise ValueError
                extra["Content-Range"] = f"bytes {start}-{end}/{total}"
                return self._send(206, n.data[start : end + 1], mime, extra)
            except ValueError:
                return self._send_json(416, {"error": "bad range"})
        self._send(200, n.data, mime, extra)

    # -- write ------------------------------------------------------------

    def do_POST(self):
        with http_request(self, "volumeServer", "post"):
            self._do_post()

    def _do_post(self):
        path = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(path.query)
        try:
            fid = FileId.parse(path.path.lstrip("/"))
        except ValueError:
            return self._send_json(400, {"error": "invalid file id"})
        hotkeys.record("needle", str(fid))
        if not self._check_write_jwt(path.path.lstrip("/")):
            return self._send_json(401, {"error": "missing or invalid jwt"})
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        ctype = self.headers.get("Content-Type", "")
        name = b""
        mime = b""
        data = body
        if ctype.startswith("multipart/form-data"):
            data, name, mime = _parse_multipart(body, ctype)
        try:
            # chaos point: error -> 500 before any write, delay -> slow
            # ack, partial -> the needle stores a truncated body
            me = f"{self.volume_server.ip}:{self.volume_server.port}"
            data = faultpoint.inject(FP_POST, ctx=me, data=data)
        except faultpoint.FaultInjected as e:
            return self._send_json(500, {"error": str(e)})
        n = Needle(cookie=fid.cookie, id=fid.key, data=data)
        if name:
            n.set(FLAG_HAS_NAME)
            n.name = name[:255]
        if mime and mime != b"application/octet-stream":
            n.set(FLAG_HAS_MIME)
            n.mime = mime
        n.append_at_ns = time.time_ns()
        try:
            size = self.store.write_needle(fid.volume_id, n)
        except KeyError:
            return self._send_json(404, {"error": f"volume {fid.volume_id} not found"})
        except DiskFullError as e:
            # typed 409: the volume/disk is full — a 4xx so no layer
            # retries HERE; the client re-assigns to a different volume
            # immediately (not on the next heartbeat)
            VOLUME_FULL_REJECT.inc()
            return self._send_json(
                409, {"error": str(e), "volumeFull": True})
        except DiskFailingError as e:
            # retryable 5xx: replicas/another assign absorb it while the
            # health machine counts the EIO toward evacuation
            return self._send_json(500, {"error": str(e)})
        except PermissionError as e:
            return self._send_json(403, {"error": str(e)})
        # replicate to peers unless this IS a replicated write
        if "replicate" not in qs.get("type", []):
            err = self.volume_server.replicate_write(fid, self.path, body, self.headers)
            if err:
                if "status 409" in err:
                    # a replica's disk filled: surface the same typed
                    # re-assign signal, not an opaque 500
                    VOLUME_FULL_REJECT.inc()
                    return self._send_json(
                        409, {"error": f"replication: {err}",
                              "volumeFull": True})
                return self._send_json(500, {"error": f"replication: {err}"})
        self._send_json(201, {"name": name.decode(errors="replace"), "size": int(size), "eTag": f"{n.checksum:x}"})

    def do_PUT(self):
        self.do_POST()

    # -- delete -----------------------------------------------------------

    def do_DELETE(self):
        with http_request(self, "volumeServer", "delete"):
            self._do_delete()

    def _do_delete(self):
        path = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(path.query)
        try:
            fid = FileId.parse(path.path.lstrip("/"))
        except ValueError:
            return self._send_json(400, {"error": "invalid file id"})
        hotkeys.record("needle", str(fid))
        if not self._check_write_jwt(path.path.lstrip("/")):
            return self._send_json(401, {"error": "missing or invalid jwt"})
        # EC volumes: tombstone + distributed fan-out to all shard holders
        if (
            self.store.find_volume(fid.volume_id) is None
            and self.store.find_ec_volume(fid.volume_id) is not None
        ):
            try:
                n = self.store.read_needle(fid.volume_id, fid.key)
                if n.cookie != fid.cookie:
                    return self._send_json(404, {"error": "cookie mismatch"})
            except KeyError:
                return self._send_json(404, {"error": "not found"})
            size = self.volume_server.delete_ec_needle_distributed(
                fid.volume_id, fid.key
            )
            return self._send_json(202, {"size": int(size)})
        try:
            n = self.store.read_needle(fid.volume_id, fid.key)
            if n.cookie != fid.cookie:
                return self._send_json(404, {"error": "cookie mismatch"})
            size = self.store.delete_needle(fid.volume_id, fid.key)
        except KeyError:
            return self._send_json(404, {"error": "not found"})
        except (DiskFullError, DiskFailingError) as e:
            # retryable 5xx, NOT the write path's 409: "re-assign" is
            # meaningless for a delete — the client's failover sends it
            # to a replica, whose fan-out tombstones this copy too
            return self._send_json(500, {"error": str(e)})
        except CorruptNeedleError as e:
            # cannot cookie-check rotten bytes; the retryable error sends
            # the delete to a healthy replica, whose fan-out tombstones
            # this copy too
            return self._send_json(
                500, {"error": f"needle corrupt, retry a replica: {e}"})
        if "replicate" not in qs.get("type", []):
            self.volume_server.replicate_delete(
                fid, self.path, self.headers.get("Authorization") or ""
            )
        self._send_json(202, {"size": int(size)})


def _parse_multipart(body: bytes, ctype: str) -> tuple[bytes, bytes, bytes]:
    """Minimal multipart/form-data parse: first file part wins."""
    boundary = None
    for piece in ctype.split(";"):
        piece = piece.strip()
        if piece.startswith("boundary="):
            boundary = piece[len("boundary="):].strip('"').encode()
    if not boundary:
        return body, b"", b""
    delim = b"--" + boundary
    # parts are separated by CRLF + delimiter; the first delimiter may have
    # no preceding CRLF, and the last is delim + b"--".  Splitting on the
    # exact separator keeps payload bytes intact (no rstrip — trailing
    # \r\n or '-' bytes in the data must survive).
    normalized = body if body.startswith(b"\r\n") else b"\r\n" + body
    for part in normalized.split(b"\r\n" + delim)[1:]:
        if part.startswith(b"--"):
            break  # closing delimiter
        if part.startswith(b"\r\n"):
            part = part[2:]
        head, sep, content = part.partition(b"\r\n\r\n")
        if not sep:
            continue
        name = b""
        mime = b""
        for line in head.split(b"\r\n"):
            low = line.lower()
            if low.startswith(b"content-disposition") and b"filename=" in low:
                fn = line.split(b"filename=")[-1].strip(b'"')
                name = fn.split(b'"')[0]
            elif low.startswith(b"content-type:"):
                mime = line.split(b":", 1)[1].strip()
        if name or content:
            return content, name, mime
    return body, b"", b""




shield_handler(VolumeHttpHandler, "_send_json")


def serve_http(volume_server, host: str, port: int):
    handler = type(
        "BoundVolumeHttpHandler",
        (VolumeHttpHandler,),
        {"volume_server": volume_server},
    )
    # the volume data port is the event-loop front end's first surface
    # (SEAWEEDFS_TPU_EVENTLOOP=off falls back to thread-per-connection)
    httpd = make_http_server((host, port), handler, surface="volume")
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd
