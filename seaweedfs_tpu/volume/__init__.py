from .server import VolumeServer  # noqa: F401
