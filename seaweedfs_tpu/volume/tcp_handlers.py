"""Experimental raw-TCP needle data path.

Reference: weed/server/volume_server_tcp_handlers_write.go — a
line-oriented protocol that skips HTTP entirely for small-blob hot
paths:

  +<fid>\\n [u32 size][data]   put      -> +OK\\n | -ERR msg\\n
  -<fid>\\n                    delete   -> +OK\\n | -ERR msg\\n
  ?<fid>\\n                    get      -> +OK <size>\\n[data] | -ERR\\n
  !\\n                         flush

Documented divergences from the reference's experimental stub:
  * gets are framed with `+OK <size>` (the reference streams unframed
    bytes, which no client can parse);
  * every response is flushed per command (request/response clients
    would deadlock on the reference's explicit-'!' flushing);
  * writes fan out to replica peers like the HTTP plane, so a TCP put
    on a replication>000 volume cannot silently diverge the replicas;
  * the listener binds 127.0.0.1 by default, and write/delete commands
    are refused when the server requires write JWTs — the protocol has
    no credential field to carry one.
"""

from __future__ import annotations

import socketserver
import struct
import threading

from ..storage.file_id import FileId
from ..storage.needle import Needle
from ..util import glog
from ..util.httpd import LISTEN_BACKLOG


class _Handler(socketserver.StreamRequestHandler):
    rbufsize = 1 << 20
    wbufsize = 1 << 20

    def handle(self) -> None:
        server = self.server.volume_server  # type: ignore[attr-defined]
        store = server.store
        while True:
            line = self.rfile.readline()
            if not line:
                return
            cmd = line.rstrip(b"\n").decode("utf-8", "replace")
            if not cmd:
                continue
            op, fid_str = cmd[0], cmd[1:]
            try:
                if op == "+":
                    # consume the frame BEFORE any validation: an early
                    # -ERR would leave the length prefix + payload in the
                    # stream to be parsed as commands (desync)
                    (size,) = struct.unpack(">I", self._read_exact(4))
                    data = self._read_exact(size)
                    if server.jwt_signing_key:
                        raise PermissionError(
                            "writes require a jwt; the tcp protocol "
                            "carries none — use the http data path")
                    fid = FileId.parse(fid_str)
                    n = Needle(cookie=fid.cookie, id=fid.key, data=data)
                    store.write_needle(fid.volume_id, n)
                    err = server.replicate_write(
                        fid, f"/{fid_str}", data, {})
                    if err:
                        raise IOError(f"replication: {err}")
                    self.wfile.write(b"+OK\n")
                elif op == "-":
                    if server.jwt_signing_key:
                        raise PermissionError(
                            "deletes require a jwt; the tcp protocol "
                            "carries none — use the http data path")
                    fid = FileId.parse(fid_str)
                    # same anti-tamper contract as the HTTP delete path:
                    # the cookie must match before anything is removed
                    n = store.read_needle(fid.volume_id, fid.key)
                    if n.cookie != fid.cookie:
                        raise PermissionError("cookie mismatch")
                    store.delete_needle(fid.volume_id, fid.key)
                    server.replicate_delete(fid, f"/{fid_str}")
                    self.wfile.write(b"+OK\n")
                elif op == "?":
                    fid = FileId.parse(fid_str)
                    n = store.read_needle(fid.volume_id, fid.key,
                                          expected_cookie=fid.cookie)
                    data = bytes(n.data)
                    self.wfile.write(f"+OK {len(data)}\n".encode())
                    self.wfile.write(data)
                elif op == "!":
                    pass
                else:
                    self.wfile.write(b"-ERR unknown command\n")
            except Exception as e:  # noqa: BLE001 — per-command errors
                self.wfile.write(f"-ERR {e}\n".encode())
            # responses flush per command: an unflushed reply deadlocks
            # request/response clients
            self.wfile.flush()

    def _read_exact(self, size: int) -> bytes:
        out = bytearray()
        while len(out) < size:
            chunk = self.rfile.read(size - len(out))
            if not chunk:
                raise EOFError("connection closed mid-frame")
            out += chunk
        return bytes(out)


class TcpServer(socketserver.ThreadingTCPServer):
    request_queue_size = LISTEN_BACKLOG
    allow_reuse_address = True
    daemon_threads = True


def serve_tcp(volume_server, port: int, host: str = "127.0.0.1") -> TcpServer:
    srv = TcpServer((host, port), _Handler)
    srv.volume_server = volume_server  # type: ignore[attr-defined]
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    glog.info("volume tcp data path on %s:%d", host, port)
    return srv
