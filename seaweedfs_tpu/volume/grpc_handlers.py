"""Volume-server gRPC service implementation.

Covers the admin surface incl. the nine erasure-coding rpcs
(reference: weed/server/volume_grpc_erasure_coding.go, volume_grpc_vacuum.go,
volume_grpc_admin.go, volume_grpc_copy.go).  EC generate/rebuild dispatch
into the codec selected per-request (`codec` field) or the server default —
this is the `-ec.codec=tpu` switch at the rpc boundary.
"""

from __future__ import annotations

import os

import grpc

from ..pb import rpc as rpclib
from ..pb import volume_server_pb2 as vs
from ..storage import types as t
from ..storage.ec import constants as ecc
from ..storage.needle import Needle, actual_size

COPY_CHUNK = 1024 * 1024

# typed rejection prefix for epoch fencing — clients/tests match on it
STALE_EPOCH_DETAIL = "stale leader epoch"


class VolumeGrpcService:
    def __init__(self, server):
        self.server = server  # VolumeServer
        self.store = server.store

    def _check_epoch(self, request, context, method: str) -> None:
        """Epoch fence on master-driven mutating rpcs: a request stamped
        with a leader epoch OLDER than the highest this node has learned
        from heartbeat acks came from a deposed leader — reject it before
        it mutates anything.  Epoch 0 (shell operators, single-master
        deployments) is unfenced and always passes."""
        epoch = getattr(request, "leader_epoch", 0)
        known = getattr(self.server, "_leader_epoch", 0)
        if epoch and known and epoch < known:
            from ..stats.metrics import STALE_EPOCH_REJECTED

            STALE_EPOCH_REJECTED.labels(method).inc()
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"{STALE_EPOCH_DETAIL} {epoch} < {known}")

    # -- volume lifecycle -------------------------------------------------

    def AllocateVolume(self, request, context):
        self.store.add_volume(
            request.volume_id,
            request.collection,
            replication=request.replication or "000",
            ttl=request.ttl,
            preallocate=request.preallocate,
            disk_type=request.disk_type,
        )
        return vs.AllocateVolumeResponse()

    def VolumeMount(self, request, context):
        if not self.store.mount_volume(request.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeMountResponse()

    def VolumeUnmount(self, request, context):
        if not self.store.unmount_volume(request.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeUnmountResponse()

    def VolumeDelete(self, request, context):
        self._check_epoch(request, context, "VolumeDelete")
        self.store.delete_volume(request.volume_id)
        return vs.VolumeDeleteResponse()

    def VolumeMarkReadonly(self, request, context):
        self._check_epoch(request, context, "VolumeMarkReadonly")
        if not self.store.mark_readonly(request.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeMarkReadonlyResponse()

    def VolumeMarkWritable(self, request, context):
        if not self.store.mark_writable(request.volume_id):
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeMarkWritableResponse()

    def VolumeStatus(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeStatusResponse(is_read_only=v.read_only)

    def VolumeConfigure(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            return vs.VolumeConfigureResponse(error="volume not found")
        from ..storage.replica_placement import ReplicaPlacement

        new_placement = ReplicaPlacement.parse(request.replication)
        # persist FIRST (the placement byte lives in the 8-byte super
        # block at the head of the .dat, super_block.go WriteSuperBlock
        # discipline), THEN mutate memory — a failed write (e.g. the .dat
        # is remote-tiered and read-only) must not leave the node
        # heartbeating a placement that never reached disk.  Under v._lock:
        # tier transitions and vacuum commits swap v._dat.
        old = v.super_block.replica_placement
        with v._lock:
            try:
                v.super_block.replica_placement = new_placement
                v._dat.write_at(0, v.super_block.to_bytes())
            except Exception as e:  # noqa: BLE001 — report, don't diverge
                v.super_block.replica_placement = old
                return vs.VolumeConfigureResponse(
                    error=f"cannot persist super block: {e}")
        return vs.VolumeConfigureResponse()

    def DeleteCollection(self, request, context):
        for loc in self.store.locations:
            for vid, v in list(loc.volumes.items()):
                if v.collection == request.collection:
                    self.store.delete_volume(vid)
        return vs.DeleteCollectionResponse()

    # -- needle ops -------------------------------------------------------

    def BatchDelete(self, request, context):
        from ..storage.file_id import FileId

        resp = vs.BatchDeleteResponse()
        for fid_str in request.file_ids:
            r = resp.results.add(file_id=fid_str)
            try:
                fid = FileId.parse(fid_str)
                if not request.skip_cookie_check:
                    n = self.store.read_needle(fid.volume_id, fid.key)
                    if n.cookie != fid.cookie:
                        r.status, r.error = 403, "cookie mismatch"
                        continue
                size = self.store.delete_needle(fid.volume_id, fid.key)
                r.status, r.size = 202, size
            except KeyError:
                r.status, r.error = 404, "not found"
            except Exception as e:  # pragma: no cover
                r.status, r.error = 500, str(e)
        return resp

    def ReadNeedleBlob(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        with v._lock:
            blob = v._dat.read_at(
                request.offset, actual_size(request.size, v.version)
            )
        return vs.ReadNeedleBlobResponse(needle_blob=blob)

    def WriteNeedleBlob(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        n = Needle.from_bytes(request.needle_blob, v.version, verify=False)
        v.append_needle(n)
        self.store.invalidate_needle(request.volume_id, n.id)
        return vs.WriteNeedleBlobResponse()

    def ReadAllNeedles(self, request, context):
        for vid in request.volume_ids:
            v = self.store.find_volume(vid)
            if v is None:
                continue
            for nv in list(v.needle_map.items_ascending()):
                n = v.read_needle(nv.key)
                yield vs.ReadAllNeedlesResponse(
                    volume_id=vid,
                    needle_id=nv.key,
                    cookie=n.cookie,
                    needle_blob=n.data,
                )

    # -- vacuum (4-phase protocol) ----------------------------------------

    def VacuumVolumeCheck(self, request, context):
        self._check_epoch(request, context, "VacuumVolumeCheck")
        ratio = self.store.check_compact_volume(request.volume_id)
        return vs.VacuumVolumeCheckResponse(garbage_ratio=ratio)

    def VacuumVolumeCompact(self, request, context):
        self._check_epoch(request, context, "VacuumVolumeCompact")
        self.store.compact_volume(request.volume_id)
        return vs.VacuumVolumeCompactResponse()

    def VacuumVolumeCommit(self, request, context):
        self._check_epoch(request, context, "VacuumVolumeCommit")
        self.store.commit_compact_volume(request.volume_id)
        v = self.store.find_volume(request.volume_id)
        return vs.VacuumVolumeCommitResponse(
            is_read_only=bool(v and v.read_only)
        )

    def VacuumVolumeCleanup(self, request, context):
        self._check_epoch(request, context, "VacuumVolumeCleanup")
        self.store.cleanup_compact_volume(request.volume_id)
        return vs.VacuumVolumeCleanupResponse()

    # -- status / sync ----------------------------------------------------

    def VolumeSyncStatus(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        return vs.VolumeSyncStatusResponse(
            volume_id=v.volume_id,
            collection=v.collection,
            replication=str(v.super_block.replica_placement),
            ttl=str(v.super_block.ttl),
            tail_offset=v.content_size,
            compact_revision=v.super_block.compaction_revision,
            idx_file_size=os.path.getsize(v.file_name() + ".idx")
            if os.path.exists(v.file_name() + ".idx")
            else 0,
        )

    def ReadVolumeFileStatus(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        base = v.file_name()
        return vs.ReadVolumeFileStatusResponse(
            volume_id=v.volume_id,
            idx_file_size=os.path.getsize(base + ".idx")
            if os.path.exists(base + ".idx")
            else 0,
            dat_file_size=v.content_size,
            file_count=v.file_count(),
            compaction_revision=v.super_block.compaction_revision,
            collection=v.collection,
        )

    # -- bulk file copy ---------------------------------------------------

    def CopyFile(self, request, context):
        if request.is_ec_volume:
            base = self.store._ec_base(request.volume_id, request.collection)
        else:
            v = self.store.find_volume(request.volume_id)
            if v is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
            v.flush()  # the on-disk .dat/.idx must include buffered appends
            base = v.file_name()
        path = base + request.ext
        if not os.path.exists(path):
            if request.ignore_source_file_not_found:
                return
            context.abort(grpc.StatusCode.NOT_FOUND, f"{path} not found")
        stop = request.stop_offset or os.path.getsize(path)
        with open(path, "rb") as f:
            sent = 0
            while sent < stop:
                chunk = f.read(min(COPY_CHUNK, stop - sent))
                if not chunk:
                    break
                sent += len(chunk)
                yield vs.CopyFileResponse(file_content=chunk)

    def VolumeCopy(self, request, context):
        """Pull a whole volume (.dat/.idx/.vif) from another volume server.
        `disk_type` places the copy on that tier (volume.tier.move)."""
        self._check_epoch(request, context, "VolumeCopy")
        loc = self.store.has_free_location(request.disk_type)
        if loc is None:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "no free slot")
        base = loc.base_name(request.volume_id, request.collection)
        src = rpclib.volume_server_stub(request.source_data_node)
        for ext in (".dat", ".idx", ".vif"):
            stream = src.CopyFile(
                vs.CopyFileRequest(
                    volume_id=request.volume_id,
                    collection=request.collection,
                    ext=ext,
                    ignore_source_file_not_found=(ext == ".vif"),
                )
            )
            _write_stream(base + ext, stream)
        self.store.mount_volume(request.volume_id)
        v = self.store.find_volume(request.volume_id)
        return vs.VolumeCopyResponse(
            last_append_at_ns=0 if v is None else v.needle_map.maximum_key
        )

    # -- erasure coding ---------------------------------------------------

    @staticmethod
    def _log_ec_dispatch(op: str, vid: int, codec: str) -> None:
        """One glog line naming the codec and codec-service mode this EC
        rpc will run under — the operator-facing answer to "did my
        -ec.codec=tpu request actually reach a device, and is it going
        through the batching service or direct dispatch?"."""
        from ..ops import codec_service
        from ..util import glog

        svc = codec_service.service_for_codec(codec) if codec else None
        glog.info("rpc %s vol=%d codec=%s dispatch=%s", op, vid,
                  codec or "(server default)",
                  svc.mode + "-service" if svc is not None else "direct")

    def VolumeEcShardsGenerate(self, request, context):
        self._check_epoch(request, context, "VolumeEcShardsGenerate")
        self._log_ec_dispatch(
            "VolumeEcShardsGenerate", request.volume_id, request.codec)
        try:
            self.store.generate_ec_shards(
                request.volume_id,
                request.collection,
                codec_name=request.codec or None,
            )
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return vs.VolumeEcShardsGenerateResponse()

    def VolumeEcShardsRebuild(self, request, context):
        self._check_epoch(request, context, "VolumeEcShardsRebuild")
        self._log_ec_dispatch(
            "VolumeEcShardsRebuild", request.volume_id, request.codec)
        try:
            rebuilt = self.store.rebuild_ec_shards(
                request.volume_id,
                request.collection,
                codec_name=request.codec or None,
            )
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except ValueError as e:
            # too few reachable source shards: a precondition, not a crash
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        except OSError as e:
            # a source died mid-rebuild; partial outputs were removed, so
            # the caller can safely retry against surviving holders
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return vs.VolumeEcShardsRebuildResponse(rebuilt_shard_ids=rebuilt)

    def VolumeEcShardsBatchRebuild(self, request, context):
        """Rebuild MANY volumes' globally-missing shards on this node in
        one rpc — the master's mass-repair orchestrator sends each
        rebuild-target node its whole slice of a dead-node batch.  Every
        volume sources remote columns through ONE shared
        MassPartialSession (cross-volume aggregated rpcs per source
        server) and mounts its rebuilt shards locally; per-volume errors
        come back in the response instead of failing the batch."""
        self._check_epoch(request, context, "VolumeEcShardsBatchRebuild")
        self._log_ec_dispatch(
            "VolumeEcShardsBatchRebuild",
            request.jobs[0].volume_id if request.jobs else 0, request.codec)
        results = self.server.mass_rebuild(
            [(j.volume_id, j.collection, j.shard_size)
             for j in request.jobs],
            codec=request.codec)
        resp = vs.VolumeEcShardsBatchRebuildResponse()
        for r in results:
            resp.results.add(
                volume_id=r["volume_id"],
                rebuilt_shard_ids=r.get("rebuilt", []),
                error=r.get("error", ""),
                used_partial=r.get("used_partial", False))
        return resp

    def VolumeEcShardsCopy(self, request, context):
        """Pull shard files from the source node (server-side pull protocol)."""
        self._check_epoch(request, context, "VolumeEcShardsCopy")
        loc = self.store.has_free_location() or self.store.locations[0]
        base = loc.base_name(request.volume_id, request.collection)
        src = rpclib.volume_server_stub(request.copy_from_data_node)

        def pull(ext: str, ignore_missing: bool = False):
            stream = src.CopyFile(
                vs.CopyFileRequest(
                    volume_id=request.volume_id,
                    collection=request.collection,
                    ext=ext,
                    is_ec_volume=True,
                    ignore_source_file_not_found=ignore_missing,
                )
            )
            _write_stream(base + ext, stream, drop_empty=ignore_missing)

        for sid in request.shard_ids:
            pull(ecc.to_ext(sid))
        if request.copy_ecx_file:
            pull(".ecx")
        if request.copy_ecj_file:
            pull(".ecj", ignore_missing=True)
        if request.copy_vif_file:
            pull(".vif", ignore_missing=True)
        return vs.VolumeEcShardsCopyResponse()

    def VolumeEcShardsDelete(self, request, context):
        self.store.delete_ec_shards(
            request.volume_id, request.collection, list(request.shard_ids)
        )
        return vs.VolumeEcShardsDeleteResponse()

    def VolumeEcShardsMount(self, request, context):
        try:
            self.store.mount_ec_shards(
                request.volume_id, request.collection, list(request.shard_ids)
            )
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return vs.VolumeEcShardsMountResponse()

    def VolumeEcShardsUnmount(self, request, context):
        self.store.unmount_ec_shards(request.volume_id, list(request.shard_ids))
        return vs.VolumeEcShardsUnmountResponse()

    def VolumeEcShardRead(self, request, context):
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        sh = ev.shards.get(request.shard_id)
        if sh is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec shard not found")
        if request.file_key:
            entry = ev._search_ecx(request.file_key)
            if entry is not None and t.size_is_deleted(entry[2]):
                # reference returns immediately after is_deleted; streaming
                # interval bytes afterwards would read as valid data
                yield vs.VolumeEcShardReadResponse(is_deleted=True)
                return
        remaining = request.size
        offset = request.offset
        while remaining > 0:
            chunk = sh.read_at(offset, min(COPY_CHUNK, remaining))
            if not chunk:
                break
            yield vs.VolumeEcShardReadResponse(data=chunk)
            offset += len(chunk)
            remaining -= len(chunk)

    def VolumeEcShardPartialApply(self, request, context):
        """Partial-sum repair source: multiply the requested LOCAL shard
        intervals by the decode-plan coefficient rows (through the
        shared codec service, so concurrent repairs batch), fold in any
        delegated same-rack partials, and stream ONE combined GF(2^8)
        sum — the rebuilder pulls rows x size bytes instead of every
        raw interval.  size=0 is a probe answered with the shard size.

        Served bytes are charged to the node's shared background-I/O
        bucket and back off while the PR 5 saturation gauges fire, so a
        rebuild storm never starves foreground reads."""
        from ..storage.ec.partial import batch_response_frames, serve_partial
        from ..storage.scrub import _saturation

        import time as _time

        server = self.server
        scrubber = getattr(server, "scrubber", None)
        backoff_depth = getattr(scrubber, "backoff_depth", 8) or 8

        def throttle(n: int) -> None:
            # bounded saturation backoff (deep foreground pools mean
            # this node is busy serving clients) + the PR 9 shared
            # bucket: repair reads and tier/scrub traffic drain ONE
            # per-node budget, so a rebuild storm cannot starve reads
            deadline = 2.0
            while _saturation() >= backoff_depth and deadline > 0:
                _time.sleep(0.05)
                deadline -= 0.05
            if scrubber is not None:
                scrubber.throttle_background(n)

        me = f"{server.ip}:{server.port}" if server else ""

        if len(request.batch):
            # cross-volume aggregation (mass repair): one rpc carries
            # coefficient columns for MANY volumes; per-volume eof/error
            # frames let the rebuilder degrade exactly the volumes a
            # dead shard breaks, never the whole batch
            def read_interval_for(vid: int, _collection: str):
                bev = self.store.find_ec_volume(vid)
                if bev is None:
                    return None

                def read_interval(sid: int, offset: int, length: int):
                    sh = bev.shards.get(sid)
                    if sh is None:
                        return None
                    buf = sh.read_at(offset, length)
                    return buf if len(buf) == length else None

                return read_interval

            yield from batch_response_frames(
                request, read_interval_for,
                stub_for=lambda addr: rpclib.volume_server_stub(
                    addr, timeout=30),
                ctx=me, throttle=throttle)
            return

        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        if request.size == 0:  # probe: shard size only
            try:
                size = ev.shard_size
            except (OSError, IOError):
                size = 0
            yield vs.VolumeEcShardPartialApplyResponse(shard_size=size)
            return

        def read_interval(sid: int, offset: int, length: int):
            sh = ev.shards.get(sid)
            if sh is None:
                return None
            buf = sh.read_at(offset, length)
            return buf if len(buf) == length else None

        try:
            acc = serve_partial(
                request, read_interval,
                stub_for=lambda addr: rpclib.volume_server_stub(
                    addr, timeout=30),
                ctx=me, throttle=throttle)
        except (IOError, ValueError) as e:
            # a missing local shard / dead delegate means the combined
            # partial would be silently wrong — fail loudly so the
            # rebuilder degrades to full fetches
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        blob = acc.tobytes()
        for at in range(0, len(blob), COPY_CHUNK):
            yield vs.VolumeEcShardPartialApplyResponse(
                data=blob[at:at + COPY_CHUNK])

    def VolumeEcBlobDelete(self, request, context):
        ev = self.store.find_ec_volume(request.volume_id)
        if ev is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "ec volume not found")
        ev.delete_needle(request.file_key)
        self.store.invalidate_needle(request.volume_id, request.file_key)
        return vs.VolumeEcBlobDeleteResponse()

    def VolumeEcShardsToVolume(self, request, context):
        try:
            self.store.ec_shards_to_volume(request.volume_id, request.collection)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return vs.VolumeEcShardsToVolumeResponse()

    # -- replica catch-up: incremental copy + tail sync -------------------
    # (reference: volume_grpc_copy_incremental.go, volume_grpc_tail.go)

    def _offset_since(self, v, since_ns: int) -> int:
        """First .dat offset whose record was appended after since_ns;
        falls back to EOF when everything predates it."""
        from ..tools.offline import scan_dat_file

        v.flush()
        if since_ns == 0:
            return v.super_block.block_size()
        for offset, n in scan_dat_file(v.file_name() + ".dat"):
            if n.append_at_ns > since_ns:
                return offset
        return v.content_size

    def VolumeIncrementalCopy(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        start = self._offset_since(v, request.since_ns)
        end = v.content_size
        with open(v.file_name() + ".dat", "rb") as f:
            f.seek(start)
            while start < end:
                chunk = f.read(min(COPY_CHUNK, end - start))
                if not chunk:
                    break
                yield vs.VolumeIncrementalCopyResponse(file_content=chunk)
                start += len(chunk)

    def VolumeTailSender(self, request, context):
        """Stream needles appended after since_ns; keep watching for new
        appends until idle_timeout_seconds passes without growth."""
        import time as _time

        from ..storage import types as _t
        from ..storage.needle import body_length

        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        pos = self._offset_since(v, request.since_ns)
        idle_deadline = _time.monotonic() + (request.idle_timeout_seconds or 2)
        dat_path = v.file_name() + ".dat"
        while _time.monotonic() < idle_deadline and context.is_active():
            v.flush()
            end = v.content_size
            if pos >= end:
                _time.sleep(0.1)
                continue
            with open(dat_path, "rb") as f:
                f.seek(pos)
                while pos < end:
                    header = f.read(_t.NEEDLE_HEADER_SIZE)
                    if len(header) < _t.NEEDLE_HEADER_SIZE:
                        break
                    n = Needle.parse_header(header)
                    body = f.read(
                        body_length(n.size if n.size > 0 else 0, v.version)
                    )
                    yield vs.VolumeTailSenderResponse(
                        needle_header=header, needle_body=body
                    )
                    pos += len(header) + len(body)
            idle_deadline = _time.monotonic() + (
                request.idle_timeout_seconds or 2
            )
        yield vs.VolumeTailSenderResponse(is_last_chunk=True)

    def _last_append_ns(self, v) -> int:
        from ..tools.offline import tail_watermark_ns

        v.flush()
        return tail_watermark_ns(v.file_name() + ".dat")

    def VolumeTailReceiver(self, request, context):
        """Pull missing appends from a replica peer into the local volume
        (volume_grpc_tail.go receiver side).  since_ns=0 means "from my own
        last append" — re-streaming records the replica already holds would
        duplicate them at EOF and balloon the .dat on every sync."""
        from .server import GRPC_PORT_OFFSET

        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        since_ns = request.since_ns or self._last_append_ns(v)
        host, _, port = request.source_volume_server.partition(":")
        source_grpc = f"{host}:{int(port) + GRPC_PORT_OFFSET}"
        stub = rpclib.volume_server_stub(source_grpc, timeout=120)
        for resp in stub.VolumeTailSender(
            vs.VolumeTailSenderRequest(
                volume_id=request.volume_id,
                since_ns=since_ns,
                idle_timeout_seconds=request.idle_timeout_seconds or 1,
            )
        ):
            if resp.is_last_chunk:
                break
            if not resp.needle_header:
                continue
            n = Needle.parse_header(bytes(resp.needle_header))
            full = Needle.from_bytes(
                bytes(resp.needle_header) + bytes(resp.needle_body),
                v.version, verify=False,
            )
            if n.size > 0:
                # replicas can hold the same needle under different append
                # timestamps (fan-out re-stamps); re-appending an extant
                # IDENTICAL record would balloon the .dat on every resync
                # and leave the replicas byte-diverged forever.  Size alone
                # is not identity — a same-length overwrite must still
                # land — so matched candidates compare content.
                existing = v.needle_map.get(n.id)
                if existing is not None and existing.size == n.size:
                    try:
                        local = v.read_needle(n.id)
                        if (local.cookie == full.cookie
                                and local.checksum == full.checksum):
                            continue
                    except Exception:  # unreadable local copy: replace it
                        pass
                v.append_needle(full)
                self.store.invalidate_needle(request.volume_id, n.id)
            else:
                # carry the origin's tombstone timestamp — a local stamp
                # would poison since_ns watermarks under clock skew
                v.delete_needle(n.id, at_ns=full.append_at_ns)
                self.store.invalidate_needle(request.volume_id, n.id)
        return vs.VolumeTailReceiverResponse()

    # -- remote tier -------------------------------------------------------

    def VolumeTierMoveDatToRemote(self, request, context):
        """Stream-upload a volume's .dat to the named remote tier backend
        and record it in the .vif (volume_grpc_tier.go; shell command
        volume.tier.upload).  Progress is streamed back per part, and
        every uploaded byte is charged to the node's shared background
        bucket (the scrubber's) so a tier move and a scrub pass together
        stay within one budget."""
        self._check_epoch(request, context, "VolumeTierMoveDatToRemote")
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        total = max(v.content_size, 1)
        sent: list[int] = [0]
        updates = []
        scrubber = getattr(self.server, "scrubber", None)

        def progress(n):
            delta = n - sent[0]
            sent[0] = n
            updates.append(n)
            if scrubber is not None:
                scrubber.throttle_background(delta)

        try:
            v.tier_to_remote(
                request.destination_backend_name,
                keep_local=request.keep_local_dat_file,
                progress=progress,
            )
        except (IOError, PermissionError) as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield vs.VolumeTierMoveDatToRemoteResponse(
            processed=sent[0] or total,
            processedPercentage=100.0,
        )

    def VolumeTierMoveDatFromRemote(self, request, context):
        v = self.store.find_volume(request.volume_id)
        if v is None:
            context.abort(grpc.StatusCode.NOT_FOUND, "volume not found")
        try:
            got = v.tier_to_local()
        except IOError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        yield vs.VolumeTierMoveDatFromRemoteResponse(
            processed=got, processedPercentage=100.0
        )

    # -- SQL-on-blob query (volume_grpc_query.go:12 + weed/query/) ---------

    def Query(self, request, context):
        from ..query import query_csv_lines, query_json_lines
        from ..storage.file_id import FileId

        filt = request.filter
        for fid_str in request.from_file_ids:
            fid = FileId.parse(fid_str)
            try:
                n = self.store.read_needle(fid.volume_id, fid.key)
            except KeyError:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"{fid_str} not found")
            if n.cookie != fid.cookie:
                context.abort(grpc.StatusCode.PERMISSION_DENIED,
                              f"cookie mismatch for {fid_str}")
            data = bytes(n.data)
            ins = request.input_serialization
            if ins.HasField("json_input"):
                records = query_json_lines(
                    data, list(request.selections),
                    field=filt.field, op=filt.operand, value=filt.value,
                    document=(ins.json_input.type.upper() == "DOCUMENT"),
                )
            elif ins.HasField("csv_input"):
                records = query_csv_lines(
                    data, list(request.selections),
                    field=filt.field, op=filt.operand, value=filt.value,
                    header=ins.csv_input.file_header_info,
                    delimiter=ins.csv_input.field_delimiter or ",",
                    comment=ins.csv_input.comments or "#",
                )
            else:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "need csv_input or json_input")
            yield vs.QueriedStripe(records=records)

    def VolumeScrub(self, request, context):
        """On-demand integrity scan (shell `volume.scrub`): one volume /
        EC volume, or the whole node when volume_id=0; an optional
        per-call rate override on the scrubber's token bucket."""
        scrubber = self.server.scrubber
        rate = request.rate_mbps or None
        try:
            if request.volume_id:
                r = scrubber.scrub_volume(request.volume_id, rate_mbps=rate)
            else:
                r = scrubber.scrub_once(rate_mbps=rate)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        findings = [
            (f"vol={f['volume_id']} kind={f['kind']} shard={f['shard_id']} "
             f"needle={f['needle_id']:x} {f['detail']}")
            for f in scrubber.recent_findings(request.volume_id or None)
        ]
        return vs.VolumeScrubResponse(
            scanned=r.get("scanned",
                          r.get("volumes", 0) + r.get("ec_volumes", 0)),
            scanned_bytes=r.get("bytes", r.get("scanned_bytes", 0)),
            corrupt_needles=r.get("corrupt_needles", 0),
            corrupt_shards=r.get("corrupt_shards", 0),
            index_repairs=r.get("index_repairs", 0),
            findings=findings[-32:],
        )

    def VolumeNeedleStatus(self, request, context):
        try:
            n = self.store.read_needle(request.volume_id, request.needle_id)
        except KeyError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        return vs.VolumeNeedleStatusResponse(
            needle_id=request.needle_id,
            cookie=n.cookie,
            size=len(n.data),
            last_modified=n.last_modified,
            crc=n.checksum & 0xFFFFFFFF,
            ttl=str(n.ttl) if n.ttl else "",
        )

    # -- server status / membership ---------------------------------------

    def VolumeServerStatus(self, request, context):
        resp = vs.VolumeServerStatusResponse()
        for loc in self.store.locations:
            # one statvfs wrapper for the whole process: the health
            # machine's poll refreshes its state + gauges on the way
            loc.health.poll()
            snap = loc.health.snapshot()
            all_b = snap["total_bytes"]
            free_b = snap["free_bytes"]
            used_b = all_b - free_b
            resp.disk_statuses.add(
                dir=loc.directory,
                all=all_b,
                used=used_b,
                free=free_b,
                percent_free=100.0 * free_b / all_b if all_b else 0.0,
                percent_used=100.0 * used_b / all_b if all_b else 0.0,
            )
        return resp

    def VolumeServerLeave(self, request, context):
        """Graceful exit from the cluster: stop heartbeating so the master
        unregisters this node (volume_server.proto:93)."""
        self.server.stop_heartbeat()
        return vs.VolumeServerLeaveResponse()


def _write_stream(path: str, stream, drop_empty: bool = False) -> None:
    wrote = False
    try:
        with open(path, "wb") as f:
            for resp in stream:
                if resp.file_content:
                    f.write(resp.file_content)
                    wrote = True
    except grpc.RpcError:
        if os.path.exists(path):
            os.remove(path)
        raise
    if drop_empty and not wrote:
        os.remove(path)
