"""VolumeServer process: HTTP data path + gRPC admin + master heartbeat.

Reference: weed/server/volume_server.go + volume_grpc_client_to_master.go.
The gRPC port is http_port + 10000 by convention, like the reference.
"""

from __future__ import annotations

import os
import threading
import time
import urllib.error

import grpc

from ..pb import master_pb2
from ..pb import rpc as rpclib
from ..security import Guard
from ..stats.metrics import (
    DISK_SIZE_GAUGE,
    REGISTRY,
    REPLICATION_ERROR,
    VOLUME_GAUGE,
    serve_metrics,
)
from ..storage.scrub import Scrubber
from ..storage.store import Store
from ..util import connpool, glog
from ..util.executors import MeteredThreadPoolExecutor
from .grpc_handlers import VolumeGrpcService
from .http_handlers import serve_http

GRPC_PORT_OFFSET = 10000


def grpc_addr(url: str) -> str:
    """http `host:port` -> its grpc address (the one port convention)."""
    host, port = url.rsplit(":", 1)
    return f"{host}:{int(port) + GRPC_PORT_OFFSET}"


def partial_enabled() -> bool:
    """SEAWEEDFS_TPU_EC_PARTIAL gate (default on) — one parse shared by
    every client-construction site."""
    return os.environ.get("SEAWEEDFS_TPU_EC_PARTIAL", "1").lower() not in (
        "0", "false", "off", "no")


class VolumeServer:
    def __init__(
        self,
        directories: list[str],
        master_addresses: list[str],
        ip: str = "127.0.0.1",
        port: int = 8080,
        public_url: str = "",
        data_center: str = "",
        rack: str = "",
        codec_name: str = "cpu",
        pulse_seconds: float = 3.0,
        max_volume_count: int | None = None,
        metrics_port: int = 0,
        jwt_signing_key: bytes | str = b"",
        whitelist: list[str] | None = None,
        tier_backends: dict | None = None,
        tcp_port: int = 0,  # experimental raw-TCP data path; 0 disables
        disk_types: list[str] | None = None,  # per-dir: hdd (default) / ssd
    ):
        # remote-tier backends: {"s3.default": {"endpoint": ..., ...}}
        # (the [storage.backend] config tier; backend.go:32-46)
        if tier_backends:
            from ..storage.backend_s3 import make_s3_backend

            for name, conf in tier_backends.items():
                btype, _, bid = name.partition(".")
                if btype == "s3":
                    make_s3_backend(bid or "default", conf)
                else:
                    glog.warning("unknown tier backend type %s", btype)
        self.ip = ip
        self.port = port
        self.tcp_port = tcp_port
        self.grpc_port = port + GRPC_PORT_OFFSET
        self.master_addresses = master_addresses
        self.pulse_seconds = pulse_seconds
        self.store = Store(
            directories,
            ip=ip,
            port=port,
            public_url=public_url,
            data_center=data_center,
            rack=rack,
            codec_name=codec_name,
            disk_types=disk_types,
        )
        if max_volume_count:
            counts: dict[str, int] = {}
            for loc in self.store.locations:
                loc.max_volume_count = max_volume_count
                counts[loc.disk_type] = (
                    counts.get(loc.disk_type, 0) + max_volume_count)
            self.store.max_volume_counts = counts
        self.current_leader: str | None = None
        # highest leader epoch (raft term) learned from heartbeat acks;
        # mutating rpcs stamped with an older epoch are rejected — a
        # deposed master cannot drive rebuilds/vacuums on this node
        self._leader_epoch = 0
        self.metrics_port = metrics_port
        self.jwt_signing_key = (
            jwt_signing_key.encode() if isinstance(jwt_signing_key, str)
            else jwt_signing_key
        )
        self.guard = Guard(whitelist)
        self._stop = threading.Event()
        self._httpd = None
        self._metricsd = None
        self._grpc_server = None
        self._hb_thread: threading.Thread | None = None
        # replica fan-out workers: writes/deletes post to every peer
        # CONCURRENTLY on pooled connections, so the client's ack waits
        # one slowest-peer RTT, not the sum over peers
        self._replica_pool = MeteredThreadPoolExecutor(
            max_workers=8, name="replica_fanout",
            thread_name_prefix="replica-fanout")
        # self-healing integrity plane: throttled background scrubber +
        # quarantine the read path feeds (SEAWEEDFS_TPU_SCRUB_RATE_MBPS=0
        # disables the daemon; on-demand volume.scrub still works)
        self.scrubber = Scrubber(self.store)
        self.store.scrubber = self.scrubber
        # every EC location cache handed to fetchers/partial clients, so
        # a master dead-node notice (heartbeat ack dead_node_seq) can
        # drop them ALL eagerly — the first post-death rebuild must not
        # plan against a dead holder and burn its liveness probe.
        # Lock-guarded: request threads register caches concurrently
        # with the heartbeat thread snapshotting the set
        import weakref

        self._loc_caches: "weakref.WeakSet" = weakref.WeakSet()
        self._loc_caches_lock = threading.Lock()
        self._dead_node_seq = 0
        # disk-fault plane: a classified write fault (ENOSPC/EIO) sets
        # this so the heartbeat generator pushes a full beat NOW — the
        # master must stop assigning to the full disk within one beat,
        # not one pulse later
        self._beat_now = threading.Event()
        self.store.on_disk_event = self._beat_now.set

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self.store.ec_fetcher_factory = self._make_ec_fetcher
        self.store.partial_client_factory = self._make_partial_client
        for loc in self.store.locations:
            for vid, ev in loc.ec_volumes.items():
                ev.remote_fetch = self._make_ec_fetcher(vid)
                ev.partial_client = self._make_partial_client(vid)
                ev.corruption_hook = self.scrubber.suspect_shard
        self.scrubber.start()
        # flight-recorder plane: always-on low-hz stack sampler feeding
        # /debug/profile/history (kill-switch + hz env knobs respected)
        from ..util import profiler as _profiler

        _profiler.ensure_continuous()
        self._httpd = serve_http(self, "0.0.0.0", self.port)
        self._grpc_server = rpclib.serve(
            [(rpclib.VOLUME_SERVER, VolumeGrpcService(self))], self.grpc_port
        )
        if self.metrics_port:
            self._metricsd = serve_metrics(self.metrics_port)
        self._tcpd = None
        if self.tcp_port:
            from .tcp_handlers import serve_tcp

            self._tcpd = serve_tcp(self, self.tcp_port)
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        glog.info("volume server started http=%d grpc=%d dirs=%s",
                  self.port, self.grpc_port,
                  ",".join(loc.directory for loc in self.store.locations))

    def stop(self) -> None:
        self._stop.set()
        self.scrubber.stop()
        if getattr(self, "_tcpd", None):
            self._tcpd.shutdown()
            self._tcpd.server_close()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._metricsd:
            self._metricsd.shutdown()
            self._metricsd.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        self._replica_pool.shutdown(wait=False)
        # NOTE: the shared EC codec service is deliberately NOT closed
        # here — it is a process-wide singleton, and tests run several
        # volume servers in one process (closing it would fail a sibling
        # server's in-flight encode with "service is closed").  Encode/
        # rebuild request threads block on their job futures, so a
        # stopping server leaves no orphan work; process exit reaps the
        # daemon scheduler, and codec_service.shutdown_all() exists for
        # owners that do want an explicit drain.
        self.store.close()

    def update_gauges(self) -> None:
        """Refresh volume/EC gauges from the store (stats/metrics.go
        volume counts incl. the ec_shards label)."""
        by_collection: dict[str, int] = {}
        ec_by_collection: dict[str, int] = {}
        size_by_collection: dict[str, int] = {}
        # zero every child first so deleted collections don't report stale
        # values on later scrapes
        for metric in (VOLUME_GAUGE, DISK_SIZE_GAUGE):
            with metric._lock:
                children = list(metric._children.values())
            for child in children:
                child.set(0)
        for loc in self.store.locations:
            for v in loc.volumes.values():
                by_collection[v.collection] = by_collection.get(v.collection, 0) + 1
                size_by_collection[v.collection] = (
                    size_by_collection.get(v.collection, 0) + v.content_size
                )
            for ev in loc.ec_volumes.values():
                ec_by_collection[ev.collection] = (
                    ec_by_collection.get(ev.collection, 0) + len(ev.shards)
                )
        for coll, n in by_collection.items():
            VOLUME_GAUGE.labels(coll, "volume").set(n)
        for coll, n in ec_by_collection.items():
            VOLUME_GAUGE.labels(coll, "ec_shards").set(n)
        for coll, n in size_by_collection.items():
            DISK_SIZE_GAUGE.labels(coll, "normal").set(n)

    def stop_heartbeat(self) -> None:
        self._stop.set()

    # -- heartbeat client -------------------------------------------------

    def _heartbeat_loop(self) -> None:
        """Reconnecting SendHeartbeat bidi stream, chasing the leader."""
        idx = 0
        while not self._stop.is_set():
            master = self.current_leader or self.master_addresses[
                idx % len(self.master_addresses)
            ]
            idx += 1
            was_leader_hint = master == self.current_leader
            try:
                self._heartbeat_once(master)
                if self.current_leader and self.current_leader != master:
                    continue  # fresh leader hint: chase it immediately
                if self.current_leader == master:
                    # the pinned master ended the stream WITHOUT naming a
                    # successor — a deposed leader cut off from its quorum
                    # does not know who won.  Unpin and rotate the seed
                    # list, or we heartbeat the minority side forever
                    self.current_leader = None
                # clean return = follower ended the stream (no leader yet):
                # back off instead of busy-spinning through the master list
                time.sleep(min(self.pulse_seconds, 1.0))
            except Exception:  # incl. grpc.RpcError
                if was_leader_hint and self.current_leader == master:
                    # the hinted leader died: fall back to seed rotation
                    # instead of hammering a dead address forever (a fresh
                    # hint set during this attempt is kept)
                    self.current_leader = None
                if self.current_leader and self.current_leader != master:
                    # deposed master handed us the new leader mid-stream:
                    # re-register NOW — backing off here is a whole
                    # election timeout of missing heartbeats
                    continue
                time.sleep(min(self.pulse_seconds, 1.0))

    def _with_stats(self, hb: master_pb2.Heartbeat) -> master_pb2.Heartbeat:
        """Attach the compact gauge/counter snapshot to a full heartbeat:
        the master's /cluster/metrics fallback when a live federation
        scrape cannot reach this node."""
        hb.stats.captured_at_ms = int(time.time() * 1000)
        for name, value in REGISTRY.snapshot_samples():
            hb.stats.samples.add(name=name, value=value)
        # confirmed scrub findings ride the same beat; re-delivered every
        # full beat until the target heals (the master keys findings
        # idempotently), so a stream that dies mid-send loses nothing
        for f in self.scrubber.outstanding_findings():
            hb.scrub_findings.add(**f)
        return hb

    def _heartbeat_once(self, master: str) -> None:
        stub = rpclib.master_stub(master)

        def requests():
            yield self._with_stats(self.store.collect_heartbeat())
            last_full = time.monotonic()
            while not self._stop.is_set():
                self._beat_now.wait(min(self.pulse_seconds / 3, 1.0))
                nv, dv, ne, de = self.store.drain_deltas()
                if nv or dv or ne or de:
                    yield master_pb2.Heartbeat(
                        ip=self.store.ip,
                        port=self.store.port,
                        public_url=self.store.public_url,
                        new_volumes=nv,
                        deleted_volumes=dv,
                        new_ec_shards=ne,
                        deleted_ec_shards=de,
                    )
                beat_now = self._beat_now.is_set()
                if (beat_now or time.monotonic() - last_full
                        >= self.pulse_seconds):
                    # a disk-fault event forces the full beat early: the
                    # read_only/disk_health bits must reach the master
                    # before the next client write lands on the full disk
                    self._beat_now.clear()
                    last_full = time.monotonic()
                    self.update_gauges()
                    yield self._with_stats(self.store.collect_heartbeat())

        for resp in stub.SendHeartbeat(requests()):
            if resp.volume_size_limit:
                self.store.volume_size_limit = resp.volume_size_limit
            # the cluster's shared background-I/O budget: scrub and
            # lifecycle tier traffic drain one per-node bucket; a push
            # of 0 WITHDRAWS a previously adopted budget (restores the
            # node's local default), so it must reach the scrubber too
            self.scrubber.set_shared_rate(resp.lifecycle_rate_mbps)
            if resp.dead_node_seq and resp.dead_node_seq != self._dead_node_seq:
                # a node died since our last beat: drop every cached EC
                # holder map NOW instead of serving the dead holder out
                # of a still-fresh TTL until the first rebuild trips on
                # it.  The seq is recorded only AFTER the invalidation
                # succeeds — recording first would let a failure here be
                # swallowed by the reconnect loop and skip this death's
                # notice forever
                dropped = self.invalidate_location_caches()
                self._dead_node_seq = resp.dead_node_seq
                glog.info(
                    "dead-node notice seq=%d (%s): invalidated %d "
                    "location cache(s)", resp.dead_node_seq,
                    ",".join(resp.dead_nodes) or "?", dropped)
            if resp.leader_epoch:
                if resp.leader_epoch < self._leader_epoch:
                    # a deposed leader still streaming acks: drop the
                    # stream and chase the real leader — adopting its
                    # budget/dead-node pushes would act on stale plans
                    if self.current_leader == master:
                        self.current_leader = None
                    raise grpc.RpcError()
                self._leader_epoch = resp.leader_epoch
            if resp.leader_grpc and resp.leader_grpc != master:
                self.current_leader = resp.leader_grpc
                raise grpc.RpcError()  # reconnect to leader
            if self._stop.is_set():
                return

    # -- remote EC shard access ------------------------------------------

    def _ec_shard_lookup(self, vid: int):
        """-> {shard_id: [(url, rack, dc), ...]} from the master (self
        excluded) — one lookup shape shared by the full-interval fetcher
        and the partial-repair client."""
        me = f"{self.ip}:{self.port}"
        master = self.current_leader or self.master_addresses[0]
        resp = rpclib.master_stub(master, timeout=5).LookupEcVolume(
            master_pb2.LookupEcVolumeRequest(volume_id=vid)
        )
        locations: dict[int, list[tuple[str, str, str]]] = {}
        for e in resp.shard_id_locations:
            held = [(loc.url, loc.rack, loc.data_center)
                    for loc in e.locations if loc.url != me]
            if held:
                locations[e.shard_id] = held
        return locations

    def _make_ec_fetcher(self, vid: int):
        """FetchFn for EcVolume: resolve shard locations via the master
        through a tiered-TTL cache (found/empty/error tiers, negative
        caching — store_ec.go:223-264) and stream the interval from the
        owning peer via VolumeEcShardRead.  The returned callable also
        exposes ``locality_of(shard_id)`` so rebuild ingress counters
        label full-interval fetches by rack/dc."""
        from ..pb import volume_server_pb2 as vs
        from ..topology.placement import ec_source_locality
        from ..wdclient.location_cache import TieredLocationCache

        cache = TieredLocationCache(lambda: self._ec_shard_lookup(vid))
        self._register_cache(cache)
        # locality of the holder each shard was LAST actually read from
        # (a same-rack peer can be down, silently shifting the read
        # cross-rack — the ingress counters must not lie about that)
        used_locality: dict[int, str] = {}

        def fetch(shard_id: int, offset: int, length: int) -> bytes | None:
            # same-rack holders first: the fallback full fetch obeys the
            # same locality preference as partial source selection
            holders = sorted(
                cache.get().get(shard_id, []),
                key=lambda h: 0 if ec_source_locality(
                    h[1], h[2], self.store.rack,
                    self.store.data_center) == "rack" else 1)
            for url, rack, dc in holders:
                try:
                    stream = rpclib.volume_server_stub(
                        grpc_addr(url), timeout=30).VolumeEcShardRead(
                        vs.VolumeEcShardReadRequest(
                            volume_id=vid, shard_id=shard_id,
                            offset=offset, size=length,
                        )
                    )
                    data = b"".join(r.data for r in stream)
                    if len(data) == length:
                        used_locality[shard_id] = ec_source_locality(
                            rack, dc, self.store.rack,
                            self.store.data_center)
                        return data
                except grpc.RpcError:
                    continue
            if holders:
                # every cached location failed — the shard likely moved;
                # force a fresh master lookup for the next attempt
                cache.invalidate()
            return None

        def locality_of(shard_id: int) -> str:
            used = used_locality.get(shard_id)
            if used is not None:
                return used
            holders = cache.get().get(shard_id, [])
            if any(ec_source_locality(r, d, self.store.rack,
                                      self.store.data_center) == "rack"
                   for _u, r, d in holders):
                return "rack"
            return "dc"

        fetch.locality_of = locality_of
        return fetch

    def _grpc_locate(self, vid: int):
        """locate() for partial clients: the master's shard->holders map
        with every holder rewritten to its grpc address."""

        def locate():
            return {
                sid: [(grpc_addr(url), rack, dc)
                      for url, rack, dc in holders]
                for sid, holders in self._ec_shard_lookup(vid).items()
            }

        return locate

    def _make_partial_client(self, vid: int):
        """PartialRepairClient for rebuilds/degraded reads on this node,
        or None when the protocol is disabled
        (SEAWEEDFS_TPU_EC_PARTIAL=0)."""
        from ..storage.ec.partial import PartialRepairClient

        if not partial_enabled():
            return None
        locate = self._grpc_locate(vid)

        client = PartialRepairClient(
            vid, "", locate,
            lambda addr: rpclib.volume_server_stub(addr, timeout=30),
            my_rack=self.store.rack, my_dc=self.store.data_center)
        self._register_cache(client._cache)
        return client

    def _register_cache(self, cache) -> None:
        with self._loc_caches_lock:
            self._loc_caches.add(cache)

    def invalidate_location_caches(self) -> int:
        """Drop every live EC holder-location cache (fetchers + partial
        clients); -> how many were invalidated."""
        with self._loc_caches_lock:
            caches = list(self._loc_caches)
        for c in caches:
            c.invalidate()
        return len(caches)

    # -- mass repair (batch rebuild target) -------------------------------

    def _ensure_ec_index(self, vid: int, collection: str) -> str:
        """Base path ready for a rebuild on this node: when we hold no
        piece of the volume yet (a spread mass-repair target), pull
        .ecx/.ecj/.vif from a surviving holder first — rebuilt shards
        without the index could never serve a read."""
        from ..pb import volume_server_pb2 as vs
        from .grpc_handlers import _write_stream

        base = self.store.ec_base_for_rebuild(vid, collection)
        if os.path.exists(base + ".ecx"):
            return base
        peers: list[str] = []
        for _sid, holders in sorted(self._ec_shard_lookup(vid).items()):
            for url, _rack, _dc in holders:
                addr = grpc_addr(url)
                if addr not in peers:
                    peers.append(addr)
        last_err: Exception | None = None
        for addr in peers:
            try:
                src = rpclib.volume_server_stub(addr, timeout=60)
                for ext, optional in ((".ecx", False), (".ecj", True),
                                      (".vif", True)):
                    # pull to a temp name, publish atomically: a crash
                    # (or non-grpc error) mid-stream must never leave a
                    # TORN .ecx that the exists() check above would
                    # trust as a valid index on the retry
                    tmp = base + ext + ".masstmp"
                    try:
                        _write_stream(tmp, src.CopyFile(
                            vs.CopyFileRequest(
                                volume_id=vid, collection=collection,
                                ext=ext, is_ec_volume=True,
                                ignore_source_file_not_found=optional)),
                            drop_empty=optional)
                    except Exception:
                        try:
                            os.remove(tmp)
                        except FileNotFoundError:
                            pass
                        raise
                    if os.path.exists(tmp):
                        os.replace(tmp, base + ext)
                return base
            except (grpc.RpcError, OSError) as e:
                last_err = e
                continue
        raise IOError(
            f"volume {vid}: no reachable holder to pull .ecx from "
            f"({last_err})")

    def mass_rebuild(self, jobs: "list[tuple[int, str, int]]",
                     codec: str = "") -> list[dict]:
        """Rebuild many volumes' globally-missing shards here, remote
        columns aggregated CROSS-VOLUME through one MassPartialSession —
        one streaming rpc per source server carries every queued
        volume's coefficient columns, feeding the codec service the
        multi-volume job mix its scheduler batches.  Per-volume failures
        (or per-volume fallback to full fetches) never stall the batch.

        ``jobs`` is [(volume_id, collection, shard_size_hint)], the hint
        coming from the master's heartbeat-learned sizes (0 = probe)."""
        from concurrent.futures import ThreadPoolExecutor

        from ..storage.ec.partial import (
            BatchedPartialClient,
            MassPartialSession,
        )

        partial_on = partial_enabled()
        session = MassPartialSession(
            lambda addr: rpclib.volume_server_stub(addr, timeout=60))
        workers = max(1, int(os.environ.get(
            "SEAWEEDFS_TPU_MASS_REBUILD_WORKERS", "4")))


        def one(job: "tuple[int, str, int]") -> dict:
            vid, collection, size_hint = job
            try:
                self._ensure_ec_index(vid, collection)
                client = None
                if partial_on:
                    client = BatchedPartialClient(
                        session, vid, collection, self._grpc_locate(vid),
                        lambda addr: rpclib.volume_server_stub(
                            addr, timeout=60),
                        my_rack=self.store.rack,
                        my_dc=self.store.data_center,
                        shard_size_hint=size_hint)
                    self._register_cache(client._cache)
                rebuilt = self.store.rebuild_ec_shards(
                    vid, collection, codec_name=codec or None,
                    partial=client, shard_size=size_hint or None)
                if rebuilt:
                    self.store.mount_ec_shards(vid, collection, rebuilt)
                return {"volume_id": vid, "rebuilt": rebuilt,
                        "used_partial": client is not None}
            except Exception as e:  # noqa: BLE001 — per-volume isolation
                glog.warning("mass rebuild vol=%d failed: %s", vid, e)
                return {"volume_id": vid, "error": str(e)[:300] or "failed"}

        try:
            if len(jobs) == 1:
                return [one(jobs[0])]
            with ThreadPoolExecutor(
                    max_workers=workers,
                    thread_name_prefix="mass-rebuild") as pool:
                return list(pool.map(one, jobs))
        finally:
            session.close()

    def delete_ec_needle_distributed(self, vid: int, needle_id: int) -> int:
        """Tombstone an EC needle locally, then fan VolumeEcBlobDelete out to
        every other shard-holding server so the delete survives degraded
        reads anywhere (store_ec_delete.go:15-33 + :35).  Returns the
        needle's size from the local .ecx."""
        from ..pb import volume_server_pb2 as vs

        size = self.store.delete_ec_needle(vid, needle_id)
        master = self.current_leader or self.master_addresses[0]
        try:
            resp = rpclib.master_stub(master, timeout=5).LookupEcVolume(
                master_pb2.LookupEcVolumeRequest(volume_id=vid)
            )
        except grpc.RpcError:
            return size
        me = f"{self.ip}:{self.port}"
        peers = {
            loc.url
            for e in resp.shard_id_locations
            for loc in e.locations
            if loc.url != me
        }
        for url in peers:
            try:
                rpclib.volume_server_stub(
                    grpc_addr(url), timeout=10).VolumeEcBlobDelete(
                    vs.VolumeEcBlobDeleteRequest(
                        volume_id=vid, file_key=needle_id
                    )
                )
            except grpc.RpcError:
                pass
        return size

    def lookup_volume_url(self, vid: int) -> str | None:
        """Public URL of some server holding vid (for read redirects)."""
        master = self.current_leader or self.master_addresses[0]
        try:
            resp = rpclib.master_stub(master, timeout=5).LookupVolume(
                master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)])
            )
        except grpc.RpcError:
            return None
        for entry in resp.volume_id_locations:
            for loc in entry.locations:
                return loc.public_url or loc.url
        return None

    # -- replication fan-out ---------------------------------------------

    def other_replica_locations(self, vid: int) -> list[str]:
        """Ask the master where the other replicas of vid live."""
        master = self.current_leader or self.master_addresses[0]
        try:
            stub = rpclib.master_stub(master, timeout=5)
            resp = stub.LookupVolume(
                master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)])
            )
        except grpc.RpcError:
            return []
        out = []
        me = self.store.public_url
        for loc in resp.volume_id_locations:
            for location in loc.locations:
                if location.url not in (me, f"{self.ip}:{self.port}"):
                    out.append(location.url)
        return out

    def replicate_write(self, fid, path: str, body: bytes, headers) -> str | None:
        """Fan the write out to every other replica CONCURRENTLY on
        pooled keep-alive connections; returns the first error (in peer
        order) or None.  Write-path semantics are unchanged — any peer
        failure still fails the client's write — but the ack now waits
        max(peer RTT) instead of sum(connect + POST) per peer."""
        v = self.store.find_volume(fid.volume_id)
        if v is None or v.super_block.replica_placement.copy_count() <= 1:
            return None
        peers = self.other_replica_locations(fid.volume_id)
        if not peers:
            return None
        sep = "&" if "?" in path else "?"
        from ..telemetry import trace
        from ..util.http_util import trace_headers

        ct = headers.get("Content-Type")
        auth = headers.get("Authorization")

        def post(peer: str) -> str | None:
            url = f"http://{peer}{path}{sep}type=replicate"
            try:
                with trace.child_span("volumeServer.replicate", peer=peer):
                    # traceparent captured inside the span so the peer's
                    # span parents to the replicate hop
                    hdrs = trace_headers()
                    if ct:
                        hdrs["Content-Type"] = ct
                    if auth:  # write jwt travels with the replica fan-out
                        hdrs["Authorization"] = auth
                    with connpool.request("POST", url, body=body,
                                          headers=hdrs, timeout=10) as r:
                        r.read()
                        if r.status >= 300:
                            return f"peer {peer} status {r.status}"
            except urllib.error.HTTPError as e:
                return f"peer {peer} status {e.code}"
            except OSError as e:
                return f"peer {peer}: {e}"
            return None

        if len(peers) == 1:
            results = [post(peers[0])]
        else:
            results = list(self._replica_pool.map(
                trace.wrap_context(post), peers))
        for err in results:
            if err:
                REPLICATION_ERROR.labels("write").inc()
                return err
        return None

    def replicate_delete(self, fid, path: str, auth: str = "") -> None:
        """Best-effort tombstone fan-out.  A failed peer no longer
        disappears silently: it logs at warning and counts
        seaweedfs_replication_error_total{op="delete"} so divergent
        replicas are visible before a failover read trips over them."""
        v = self.store.find_volume(fid.volume_id)
        if v is None or v.super_block.replica_placement.copy_count() <= 1:
            return
        peers = self.other_replica_locations(fid.volume_id)
        if not peers:
            return
        sep = "&" if "?" in path else "?"
        from ..telemetry import trace
        from ..util.http_util import trace_headers

        def delete(peer: str) -> None:
            url = f"http://{peer}{path}{sep}type=replicate"
            hdrs = trace_headers()
            if auth:
                hdrs["Authorization"] = auth
            try:
                with connpool.request("DELETE", url, headers=hdrs,
                                      timeout=10) as r:
                    r.read()
            except OSError as e:  # incl. HTTPError (4xx/5xx from the peer)
                REPLICATION_ERROR.labels("delete").inc()
                glog.warning("replicate delete %s to peer %s failed: %s",
                             path, peer, e)

        if len(peers) == 1:
            delete(peers[0])
        else:
            list(self._replica_pool.map(trace.wrap_context(delete), peers))
