"""FTP gateway: an RFC 959 server over the filer namespace.

Reference surface: weed/ftpd/ — an 81-LoC stub that registers flags but
serves nothing.  This implementation is functional: a threaded control
loop speaking the classic command set (USER/PASS, PWD/CWD/CDUP, TYPE,
PASV/EPSV, LIST/NLST, RETR/STOR/APPE, DELE, MKD/RMD, RNFR/RNTO, SIZE,
MDTM, QUIT) with passive-mode data connections, every operation mapped
onto the filer's HTTP/gRPC surface (FilerClient) the same way the WebDAV
gateway maps DAV verbs.

Auth: anonymous by default; pass users={"name": "password"} to require a
match.  Active (PORT) mode is not offered — PASV/EPSV only, which every
modern client (including stdlib ftplib) uses.
"""

from __future__ import annotations

import contextlib
import socket
import socketserver
import threading
import time

from ..pb import filer_pb2
from ..s3api.filer_client import FilerClient
from ..util import glog
from ..util.httpd import LISTEN_BACKLOG


def _norm(path: str) -> str:
    parts = []
    for p in path.split("/"):
        if not p or p == ".":
            continue
        if p == "..":
            if parts:
                parts.pop()
        else:
            parts.append(p)
    return "/" + "/".join(parts)


def _split(path: str) -> tuple[str, str]:
    path = path.rstrip("/") or "/"
    if path == "/":
        return "/", ""
    i = path.rindex("/")
    return (path[:i] or "/"), path[i + 1:]


class _Handler(socketserver.StreamRequestHandler):
    server: "FtpServer"

    def handle(self) -> None:  # noqa: C901 — a protocol switch is a switch
        self.cwd = "/"
        self.user = ""
        self.authed = not self.server.users
        self.rename_from = ""
        self.pasv: socket.socket | None = None
        self.reply(220, "seaweedfs-tpu FTP gateway ready")
        while True:
            line = self.rfile.readline()
            if not line:
                break
            try:
                text = line.decode("utf-8", errors="replace").rstrip("\r\n")
            except Exception:
                continue
            cmd, _, arg = text.partition(" ")
            cmd = cmd.upper()
            try:
                if not self.dispatch(cmd, arg):
                    break
            except ConnectionError:
                break
            except Exception as e:  # noqa: BLE001 — one op fails, not the session
                glog.warning(f"ftp: {cmd} failed: {e!r}")
                self.reply(550, f"action failed: {type(e).__name__}")
        self._close_pasv()

    # -- plumbing ----------------------------------------------------------

    def reply(self, code: int, text: str) -> None:
        self.wfile.write(f"{code} {text}\r\n".encode())

    def _close_pasv(self) -> None:
        if self.pasv is not None:
            try:
                self.pasv.close()
            except OSError:
                pass
            self.pasv = None

    def _data_conn(self) -> socket.socket | None:
        """Accept the client's connection on the passive socket.

        Only the control-connection peer may claim the data port: on a
        non-loopback bind, a stranger racing to the advertised port first
        could otherwise read RETR payloads or inject STOR content without
        authenticating (classic PASV hijack).  Mismatched peers are closed
        and the accept loop continues within the deadline.
        """
        if self.pasv is None:
            self.reply(425, "use PASV or EPSV first")
            return None
        deadline = time.monotonic() + 30
        expected_ip = self.client_address[0]
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.reply(425, "data connection failed")
                    return None
                self.pasv.settimeout(remaining)
                try:
                    conn, peer = self.pasv.accept()
                except OSError:
                    self.reply(425, "data connection failed")
                    return None
                if peer[0] == expected_ip:
                    return conn
                try:
                    conn.close()
                except OSError:
                    pass
        finally:
            self._close_pasv()

    def _resolve(self, arg: str) -> str:
        if not arg:
            return self.cwd
        if arg.startswith("/"):
            return _norm(arg)
        return _norm(self.cwd.rstrip("/") + "/" + arg)

    @property
    def fc(self) -> FilerClient:
        return self.server.filer_client

    def _is_dir(self, path: str) -> bool:
        if path == "/":
            return True
        d, n = _split(path)
        e = self.fc.find_entry(d, n)
        return e is not None and e.is_directory

    # -- command dispatch --------------------------------------------------

    def dispatch(self, cmd: str, arg: str) -> bool:
        if cmd == "QUIT":
            self.reply(221, "bye")
            return False
        if cmd == "USER":
            self.user = arg
            if self.authed:
                self.reply(230, "ok, no password needed")
            else:
                self.reply(331, "password required")
            return True
        if cmd == "PASS":
            if self.authed:
                self.reply(230, "already logged in")
            elif self.server.users.get(self.user) == arg:
                self.authed = True
                self.reply(230, "logged in")
            else:
                self.reply(530, "login incorrect")
            return True
        if not self.authed:
            self.reply(530, "log in first")
            return True
        handler = getattr(self, f"do_{cmd}", None)
        if handler is None:
            self.reply(502, f"{cmd} not implemented")
            return True
        handler(arg)
        return True

    # -- session state -----------------------------------------------------

    def do_SYST(self, arg: str) -> None:
        self.reply(215, "UNIX Type: L8")

    def do_NOOP(self, arg: str) -> None:
        self.reply(200, "ok")

    def do_TYPE(self, arg: str) -> None:
        self.reply(200, f"type {arg or 'I'} ok")

    def do_FEAT(self, arg: str) -> None:
        self.wfile.write(b"211-features\r\n SIZE\r\n MDTM\r\n EPSV\r\n")
        self.reply(211, "end")

    def do_PWD(self, arg: str) -> None:
        self.reply(257, f'"{self.cwd}" is the current directory')

    def do_CWD(self, arg: str) -> None:
        target = self._resolve(arg)
        if self._is_dir(target):
            self.cwd = target
            self.reply(250, f"cwd is now {target}")
        else:
            self.reply(550, f"{target}: not a directory")

    def do_CDUP(self, arg: str) -> None:
        self.do_CWD("..")

    # -- passive data ------------------------------------------------------

    def _open_pasv(self) -> int:
        self._close_pasv()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.bind((self.server.ip, 0))
        s.listen(1)
        self.pasv = s
        return s.getsockname()[1]

    def do_PASV(self, arg: str) -> None:
        port = self._open_pasv()
        # advertise the control connection's local address, not the bind
        # address — `-ip 0.0.0.0` must not leak into the 227 reply
        host = self.connection.getsockname()[0]
        h = host.replace(".", ",")
        self.reply(227, f"entering passive mode ({h},{port >> 8},{port & 255})")

    def do_EPSV(self, arg: str) -> None:
        port = self._open_pasv()
        self.reply(229, f"entering extended passive mode (|||{port}|)")

    # -- directory ops -----------------------------------------------------

    def _list_lines(self, path: str, names_only: bool) -> list[bytes]:
        if self._is_dir(path):
            entries = list(self.fc.iter_entries(path))
        else:
            d, n = _split(path)
            e = self.fc.find_entry(d, n)
            entries = [e] if e is not None else []
        lines = []
        for e in entries:
            if names_only:
                lines.append(e.name.encode() + b"\r\n")
                continue
            kind = "d" if e.is_directory else "-"
            size = e.attributes.file_size
            mtime = time.strftime(
                "%b %d %H:%M", time.localtime(e.attributes.mtime or 0))
            lines.append(
                f"{kind}rw-r--r-- 1 weed weed {size:>12} {mtime} "
                f"{e.name}\r\n".encode())
        return lines

    def do_LIST(self, arg: str) -> None:
        # ls-style flags come first; stop stripping at the first non-flag
        # token and keep the remainder verbatim (names may contain spaces
        # or later dashes)
        tokens = arg.split(" ")
        while tokens and tokens[0].startswith("-"):
            tokens.pop(0)
        self._send_listing(self._resolve(" ".join(tokens)), names_only=False)

    def do_NLST(self, arg: str) -> None:
        self._send_listing(self._resolve(arg), names_only=True)

    def _send_listing(self, path: str, names_only: bool) -> None:
        lines = self._list_lines(path, names_only)
        self.reply(150, "directory listing follows")
        conn = self._data_conn()
        if conn is None:
            return
        try:
            for ln in lines:
                conn.sendall(ln)
        finally:
            conn.close()
        self.reply(226, "listing sent")

    def do_MKD(self, arg: str) -> None:
        path = self._resolve(arg)
        d, n = _split(path)
        self.fc.mkdir(d, n)
        self.reply(257, f'"{path}" created')

    def do_RMD(self, arg: str) -> None:
        path = self._resolve(arg)
        if not self._is_dir(path):
            self.reply(550, f"{path}: not a directory")
            return
        d, n = _split(path)
        self.fc.delete_entry(d, n, is_recursive=True)
        self.reply(250, f"{path} removed")

    # -- file ops ----------------------------------------------------------

    def do_SIZE(self, arg: str) -> None:
        d, n = _split(self._resolve(arg))
        e = self.fc.find_entry(d, n)
        if e is None or e.is_directory:
            self.reply(550, "no such file")
        else:
            self.reply(213, str(e.attributes.file_size))

    def do_MDTM(self, arg: str) -> None:
        d, n = _split(self._resolve(arg))
        e = self.fc.find_entry(d, n)
        if e is None:
            self.reply(550, "no such file")
        else:
            self.reply(213, time.strftime(
                "%Y%m%d%H%M%S", time.gmtime(e.attributes.mtime or 0)))

    def do_RETR(self, arg: str) -> None:
        path = self._resolve(arg)
        try:
            resp = self.fc.open_object(path)  # streaming GET
        except Exception:
            self.reply(550, f"{path}: not found")
            return
        self.reply(150, f"opening data connection for {path}")
        conn = self._data_conn()
        if conn is None:
            resp.close()
            return
        try:
            while True:
                buf = resp.read(1 << 16)
                if not buf:
                    break
                conn.sendall(buf)
        finally:
            conn.close()
            resp.close()
        self.reply(226, "transfer complete")

    def _recv_to_spool(self, conn: socket.socket):
        """Drain a data connection into a spooled temp file (RAM under
        8MB, disk beyond) so multi-GB transfers never live in memory."""
        import tempfile

        spool = tempfile.SpooledTemporaryFile(max_size=8 << 20)
        try:
            while True:
                buf = conn.recv(1 << 16)
                if not buf:
                    break
                spool.write(buf)
        finally:
            conn.close()
        return spool

    def do_STOR(self, arg: str) -> None:
        path = self._resolve(arg)
        self.reply(150, f"ok to send data for {path}")
        conn = self._data_conn()
        if conn is None:
            return
        with self._recv_to_spool(conn) as spool:
            length = spool.tell()
            spool.seek(0)
            self.fc.put_object_stream(path, spool, length)
        self.reply(226, "stored")

    def do_APPE(self, arg: str) -> None:
        path = self._resolve(arg)
        self.reply(150, f"ok to append data for {path}")
        conn = self._data_conn()
        if conn is None:
            return
        with self._recv_to_spool(conn) as spool:
            # read-modify-write append, serialized per path WITHIN this
            # gateway (a filer-side atomic append does not exist; two
            # gateways appending the same path can still lose an update,
            # as with any FTP server backed by whole-object PUTs)
            with self.server.path_lock(path):
                import tempfile

                merged = tempfile.SpooledTemporaryFile(max_size=8 << 20)
                try:
                    resp = self.fc.open_object(path)
                    while True:
                        buf = resp.read(1 << 16)
                        if not buf:
                            break
                        merged.write(buf)
                    resp.close()
                except Exception:
                    pass
                spool.seek(0)
                while True:
                    buf = spool.read(1 << 16)
                    if not buf:
                        break
                    merged.write(buf)
                length = merged.tell()
                merged.seek(0)
                with merged:
                    self.fc.put_object_stream(path, merged, length)
        self.reply(226, "appended")

    def do_DELE(self, arg: str) -> None:
        path = self._resolve(arg)
        d, n = _split(path)
        if self.fc.find_entry(d, n) is None:
            self.reply(550, f"{path}: no such file")
            return
        self.fc.delete_entry(d, n)
        self.reply(250, f"{path} deleted")

    def do_RNFR(self, arg: str) -> None:
        self.rename_from = self._resolve(arg)
        self.reply(350, "ready for RNTO")

    def do_RNTO(self, arg: str) -> None:
        if not self.rename_from:
            self.reply(503, "RNFR first")
            return
        src, dst = self.rename_from, self._resolve(arg)
        self.rename_from = ""
        sd, sn = _split(src)
        dd, dn = _split(dst)
        stub = self.fc.stub()
        stub.AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
            old_directory=sd, old_name=sn,
            new_directory=dd, new_name=dn,
        ))
        self.reply(250, f"renamed to {dst}")


class _ThreadedTCP(socketserver.ThreadingTCPServer):
    request_queue_size = LISTEN_BACKLOG
    allow_reuse_address = True
    daemon_threads = True


class FtpServer:
    """`weed ftp`: serve the filer namespace over FTP."""

    def __init__(self, filer: str = "127.0.0.1:8888", ip: str = "127.0.0.1",
                 port: int = 8021, users: dict[str, str] | None = None):
        self.ip = ip
        self.port = port
        self.users = users or {}
        self.filer_client = FilerClient(filer)
        self._srv = _ThreadedTCP((ip, port), _Handler)
        self._srv.filer_client = self.filer_client  # type: ignore[attr-defined]
        self._srv.users = self.users  # type: ignore[attr-defined]
        self._srv.ip = ip  # type: ignore[attr-defined]
        self._srv.path_lock = self.path_lock  # type: ignore[attr-defined]
        self.port = self._srv.server_address[1]
        self._thread: threading.Thread | None = None
        self._path_locks: dict[str, list] = {}  # path -> [Lock, refcount]
        self._path_locks_guard = threading.Lock()

    @contextlib.contextmanager
    def path_lock(self, path: str):
        """Per-path mutex for read-modify-write ops (APPE) in this process.

        Refcounted: the entry is evicted once the last holder releases, so
        a long-lived gateway serving many distinct paths doesn't grow an
        unbounded lock table.
        """
        with self._path_locks_guard:
            entry = self._path_locks.get(path)
            if entry is None:
                entry = self._path_locks[path] = [threading.Lock(), 0]
            entry[1] += 1
        entry[0].acquire()
        try:
            yield
        finally:
            entry[0].release()
            with self._path_locks_guard:
                entry[1] -= 1
                if entry[1] == 0 and self._path_locks.get(path) is entry:
                    del self._path_locks[path]

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._srv.serve_forever, name="ftp-server", daemon=True)
        self._thread.start()
        glog.info(f"ftp gateway on {self.ip}:{self.port}")

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
