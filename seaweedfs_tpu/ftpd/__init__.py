from .server import FtpServer  # noqa: F401
