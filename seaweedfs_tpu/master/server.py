"""MasterServer: placement metadata owner, out of the data path.

Reference: weed/server/master_server.go.  Single-master mode this round;
the leader() hook is where raft slots in.  Includes the volume growth path
(grow -> AllocateVolume on chosen volume servers), the vacuum sweep, and a
maintenance loop that runs EC encode/rebuild/balance periodically like the
reference's [master.maintenance] script block (master_server.go:187-242).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..util.httpd import FrameworkHTTPServer

import grpc

from ..pb import master_pb2
from ..pb import rpc as rpclib
from ..pb import volume_server_pb2 as vs
from ..stats.metrics import serve_metrics
from ..telemetry import http_request, record_op, serve_debug_http
from ..storage.replica_placement import ReplicaPlacement
from ..util import glog
from ..topology.placement import Candidate, pick_nodes_for_write
from ..topology.topology import Topology
from ..topology.volume_layout import VolumeLayout
from .grpc_handlers import MasterGrpcService
from .sequence import make_sequencer

GRPC_PORT_OFFSET = 10000


class _Unrepairable(Exception):
    """A scrub finding with no repair path (no healthy replica, node
    gone): parked as `unrepairable` instead of burning retry attempts."""


class MasterServer:
    def __init__(
        self,
        ip: str = "127.0.0.1",
        port: int = 9333,
        volume_size_limit_mb: int = 30 * 1024,
        default_replication: str = "000",
        pulse_seconds: float = 3.0,
        sequencer: str = "memory",
        sequencer_node_id: int = 0,  # snowflake worker id
        sequencer_etcd_urls: str = "127.0.0.1:2379",
        garbage_threshold: float = 0.3,
        maintenance_interval: float = 0.0,  # seconds; 0 disables
        maintenance_script: list[str] | None = None,  # None = default suite
        metrics_port: int = 0,
        jwt_signing_key: bytes | str = b"",
        peers: list[str] | None = None,  # master quorum (ip:port HTTP addrs)
        raft_state_dir: str = "",
        lifecycle_interval: float = 0.0,  # seconds; 0 = manual only
        lifecycle_dir: str = "",          # journal dir; "" = memory only
        lifecycle_rate_mbps: float | None = None,  # None = env, 0 = off
        lifecycle_policy: dict | None = None,
        repair_deadline_s: float | None = None,  # None = env, 0 = no bound
        peer_clusters: list[str] | None = None,  # remote master http addrs
        slo_interval: float = 0.0,    # SLO evaluation tick; 0 = on demand
        slo_specs: list | None = None,  # None = default_specs()
        slo_window_scale: float | None = None,  # None = env, 1.0 = real-time
        canary_interval: float = 0.0,  # black-box probe tick; 0 disables
        canary_s3: str = "",           # S3 gateway addr for metadata probes
        alert_webhook: str = "",       # POST alert transitions here
        debug_dir: str = "",           # flight-recorder bundle directory
    ):
        self.ip = ip
        self.port = port
        self.grpc_port = port + GRPC_PORT_OFFSET
        self.topo = Topology(
            volume_size_limit=volume_size_limit_mb * (1 << 20),
            pulse_seconds=pulse_seconds,
        )
        self.default_replication = default_replication
        self.garbage_threshold = garbage_threshold
        self.maintenance_interval = maintenance_interval
        self.maintenance_script = maintenance_script
        self.sequencer = make_sequencer(
            sequencer, sequencer_node_id,
            etcd_endpoint=sequencer_etcd_urls.split(",")[0])
        self.layouts: dict[tuple[str, str, str], VolumeLayout] = {}
        self._layout_lock = threading.RLock()
        self._subscribers: list = []
        self._sub_lock = threading.Lock()
        self._admin_locks: dict[str, int] = {}
        self._admin_lock_mutex = threading.Lock()
        self._grow_locks: dict[tuple, threading.Lock] = {}
        self._grow_locks_guard = threading.Lock()
        self._stop = threading.Event()
        self._grpc_server = None
        self._httpd = None
        self._metricsd = None
        self.metrics_port = metrics_port
        # observability plane: registered non-volume clients (filers via
        # KeepConnected), last-heartbeat stats snapshots per instance,
        # and the bounded fan-out pool /cluster/{metrics,traces} scrape on
        self.clients: dict[str, dict] = {}
        self._clients_lock = threading.Lock()
        self.stats_snapshots: dict[str, dict] = {}
        self._snapshots_lock = threading.Lock()
        # self-healing plane: corruption findings from volume-server scrub
        # daemons (heartbeat field 18), keyed for idempotent re-reports;
        # the maintenance loop's repair pass drains them
        self.scrub_findings: dict[tuple, dict] = {}
        self._scrub_lock = threading.Lock()
        # serializes repair passes (maintenance loop vs /vol/repair): a
        # concurrent pass would VolumeUnmount mid-VolumeCopy
        self._repair_mutex = threading.Lock()
        # vids the scrub repair pass is healing RIGHT NOW — the mass
        # repair orchestrator skips them (and the pass skips volumes
        # with an active mass_repair journal job: one repairer at a
        # time).  Claims on BOTH sides happen under _repair_claim_lock:
        # the pass registers its volume set and snapshots the journal
        # atomically, and the orchestrator journals its jobs while
        # reading this set — without the shared lock a death arriving
        # mid-pass could interleave check-then-act on the same volume
        self._scrub_repairing: set[int] = set()
        self._repair_claim_lock = threading.Lock()
        # dead-node announcements for the heartbeat ack: volume servers
        # seeing a newer seq drop their EC holder-location caches NOW
        self.dead_node_seq = 0
        self.recent_dead_nodes: list[str] = []
        from ..util.executors import MeteredThreadPoolExecutor

        self.federation_pool = MeteredThreadPoolExecutor(
            max_workers=8, name="federation",
            thread_name_prefix="federation")
        self.jwt_signing_key = (
            jwt_signing_key.encode() if isinstance(jwt_signing_key, str)
            else jwt_signing_key
        )
        # lifecycle plane (maintenance/): policy-driven seal -> EC ->
        # tier -> vacuum -> rebalance with a crash-safe job journal.
        # Constructed unconditionally so /cluster/lifecycle and the
        # volume.lifecycle shell command work even when the periodic
        # loop is disabled (interval 0)
        from ..maintenance import LifecycleController, PolicySet

        self.lifecycle = LifecycleController(
            self,
            policies=(PolicySet.parse(lifecycle_policy)
                      if lifecycle_policy is not None else None),
            interval_s=lifecycle_interval,
            rate_mbps=lifecycle_rate_mbps,
            journal_dir=lifecycle_dir,
        )
        # dead-node mass repair (ISSUE 11): rides the lifecycle journal
        # for crash-safe, duplicate-suppressed jobs; triggered from the
        # liveness sweep, executed as one batched rebuild rpc per target
        from ..maintenance import MassRepairOrchestrator

        self.mass_repair = MassRepairOrchestrator(
            self, self.lifecycle, deadline_s=repair_deadline_s)
        # geo scenario (ISSUE 12): the peer-cluster registry behind
        # GET /cluster/geo — remote master addresses this cluster
        # replicates with; link health/lag comes from the filer
        # heartbeat stats snapshots (the seaweedfs_geo_* families)
        self.peer_clusters = [p.strip() for p in (peer_clusters or [])
                              if p.strip()]
        # judgment plane (ISSUE 13): the SLO engine evaluates burn-rate
        # rules over family-filtered federation scrapes; the canary
        # prober feeds it active black-box SLIs.  Both are constructed
        # unconditionally so /cluster/alerts and the shell work on a
        # manually driven master (engine interval 0 = evaluate-on-read;
        # canary interval 0 = disabled).
        from ..stats.metrics import REGISTRY as _registry
        from ..telemetry.canary import CanaryProber
        from ..telemetry.slo import SloEngine, WebhookSink, log_sink

        from . import observability as _obs

        # flight recorder (ISSUE 20): alert-triggered cluster debug
        # bundles.  Constructed before the SLO engine so a transition to
        # firing captures a bundle through its sink; manual captures run
        # via /cluster/debug/capture and the cluster.debug shell command
        from .flight import FlightRecorder

        self.flight = FlightRecorder(self, debug_dir=debug_dir)
        sinks = [log_sink, self.flight.sink]
        if alert_webhook:
            sinks.append(WebhookSink(alert_webhook))
        self.slo = SloEngine(
            scrape=lambda fams: _obs.cluster_metrics(self, fams),
            specs=slo_specs,
            sinks=sinks,
            interval_s=slo_interval,
            exemplars=_registry.exemplars,
            window_scale=slo_window_scale,
        )
        self.canary = CanaryProber(
            self, interval_s=canary_interval, s3_address=canary_s3)
        self._rng = random.Random()
        # raft quorum (raft_server.go:21-46): multi-master when peers given
        self.raft = None
        addr = f"{ip}:{port}"
        peer_list = [p.strip() for p in (peers or []) if p.strip()]
        if peer_list:
            if addr not in peer_list:
                # silently falling back to single-master here would give
                # every quorum member is_leader()=True -> split brain
                raise ValueError(
                    f"this master {addr!r} is not in -peers {peer_list}; "
                    "include its own ip:port in the quorum list"
                )
            if len(peer_list) > 1:
                from .raft import RaftNode

                state_path = (
                    f"{raft_state_dir}/raft-{port}.json"
                    if raft_state_dir else ""
                )
                self.raft = RaftNode(
                    addr, peer_list, self._raft_send,
                    apply_fn=self._raft_apply, state_path=state_path,
                )
        # leader-fenced control plane (ISSUE 17): the warm-up barrier
        # holds assigns and repair planning on a freshly elected leader
        # until the committed log tail is applied and a heartbeat cycle
        # has been seen; role transitions fence the deposed side.
        self._warmed = threading.Event()
        self._beat_count = 0  # full-state heartbeats processed as leader
        if self.raft is None:
            self._warmed.set()  # single master: always warm
        else:
            self.raft.on_role_change = self._on_role_change
            # lifecycle + mass-repair journal records replicate through
            # the raft log; every quorum member mirrors the job set
            self.lifecycle.journal.proposer = self._journal_propose

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        self._grpc_server = rpclib.serve(
            [(rpclib.MASTER, MasterGrpcService(self))], self.grpc_port
        )
        self._httpd = _serve_http(self, "0.0.0.0", self.port)
        if self.metrics_port:
            self._metricsd = serve_metrics(self.metrics_port)
        # flight-recorder plane: always-on low-hz stack sampler feeding
        # /debug/profile/history (kill-switch + hz env knobs respected)
        from ..util import profiler as _profiler

        _profiler.ensure_continuous()
        threading.Thread(target=self._liveness_loop, daemon=True).start()
        if self.maintenance_interval > 0:
            threading.Thread(target=self._maintenance_loop, daemon=True).start()
        self.lifecycle.start()
        self.slo.start()
        self.canary.start()
        if self.is_leader():
            # journaled mass-repair jobs interrupted by a crash replay
            # as pending — resume them exactly-once from the journal
            self.mass_repair.resume()
        if self.raft is not None:
            self.raft.start()
        glog.info("master started http=%d grpc=%d peers=%d",
                  self.port, self.grpc_port,
                  len(self.raft.peers) + 1 if self.raft else 1)

    def stop(self) -> None:
        self._stop.set()
        self.canary.stop()
        self.slo.stop()
        self.mass_repair.stop()
        self.lifecycle.stop()
        if self.raft is not None:
            self.raft.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._metricsd:
            self._metricsd.shutdown()
            self._metricsd.server_close()
        if self._grpc_server:
            self._grpc_server.stop(grace=0.5)
        self.federation_pool.shutdown(wait=False)

    # -- raft plumbing ----------------------------------------------------

    def _raft_sig(self, payload: bytes) -> str:
        import hashlib
        import hmac

        return hmac.new(
            self.jwt_signing_key, payload, hashlib.sha256
        ).hexdigest()

    def _raft_send(self, peer: str, msg: dict) -> dict | None:
        from ..util import connpool

        payload = json.dumps(msg).encode()
        headers = {"Content-Type": "application/json"}
        if self.jwt_signing_key:
            # consensus messages forge cluster state; sign them with the
            # same shared secret that protects writes (security/jwt.go)
            headers["X-Raft-Signature"] = self._raft_sig(payload)
        with connpool.request(
                "POST", f"http://{peer}/cluster/raft", body=payload,
                headers=headers, timeout=1.0) as r:
            return json.loads(r.read())

    def verify_raft_request(self, payload: bytes, signature: str) -> bool:
        import hmac

        if not self.jwt_signing_key:
            return True
        return hmac.compare_digest(self._raft_sig(payload), signature or "")

    def _raft_apply(self, cmd: dict):
        """State machine: the reference's MaxVolumeIdCommand analogue.

        "inc_vid" computes the new id HERE (in log order, identically on
        every replica) — a fresh leader first applies the old leader's
        tail, so it can never re-issue an id committed before failover."""
        op = cmd.get("op")
        if op == "inc_vid":
            with self.topo.lock:
                self.topo.max_volume_id += 1
                return self.topo.max_volume_id
        if op == "max_vid":  # older persisted logs
            with self.topo.lock:
                self.topo.max_volume_id = max(
                    self.topo.max_volume_id, int(cmd["value"])
                )
                return self.topo.max_volume_id
        if op == "journal":  # lifecycle/mass-repair job record mirror
            self.lifecycle.journal.apply_replicated(cmd["rec"])
            return True
        if op == "journal_drop":
            self.lifecycle.journal.apply_drop(cmd["key"])
            return True
        if op == "barrier":  # warm-up: committing this proves the new
            return True      # leader has applied every prior entry
        return None

    def _journal_propose(self, op: str, payload: dict) -> bool:
        """JobJournal proposer: replicate one journal mutation through
        raft; False (-> the journal raises) when not the leader or the
        quorum is unreachable."""
        if op == "drop":
            return self.raft.propose(
                {"op": "journal_drop", "key": payload["key"]})
        return self.raft.propose({"op": "journal", "rec": payload})

    def _on_role_change(self, role: str, term: int) -> None:
        """Raft leadership transition (fires from a raft daemon thread).

        Deposed: fence the whole control plane NOW — cancel lifecycle
        executor queues and running mass-repair waves so this master
        stops racing the new leader (its in-flight rpcs are additionally
        rejected volume-server-side by epoch).

        Elected: warm-up barrier before serving — (1) commit a barrier
        entry, which proves the old leader's committed tail (journal
        records, vid increments) is applied here; (2) wait for one
        heartbeat cycle (bounded) so assigns see real topology; then
        resume journaled jobs exactly-once."""
        if role != "leader":
            self._warmed.clear()
            self.lifecycle.fence(term)
            self.mass_repair.fence(term)
            glog.warning("master %s:%d deposed at term %d — "
                         "control plane fenced", self.ip, self.port, term)
            return
        self._warmed.clear()
        beats0 = self._beat_count
        if not self.raft.propose({"op": "barrier"}, timeout=10.0):
            glog.warning("master %s:%d elected at term %d but barrier "
                         "did not commit (deposed again?)",
                         self.ip, self.port, term)
            return
        grace = float(os.environ.get("SEAWEEDFS_TPU_WARMUP_GRACE_S", "2.0"))
        deadline = time.monotonic() + grace
        while (time.monotonic() < deadline
               and self._beat_count == beats0
               and self.raft.is_leader()
               and not self._stop.is_set()):
            time.sleep(0.05)
        if not self.raft.is_leader() or self._stop.is_set():
            return
        resumed = self.lifecycle.journal.resume_stale_running()
        self._warmed.set()
        glog.info("master %s:%d warmed up at term %d (resumed=%d)",
                  self.ip, self.port, term, resumed)
        # journaled jobs inherited from the deposed leader restart
        # exactly-once: the replicated journal is the dedup memory
        self.mass_repair.resume()

    def control_warmed(self) -> bool:
        """True once this master may hand out fids / plan repairs: not
        mid-failover-warm-up (always true without raft)."""
        return self._warmed.is_set()

    def leader_epoch(self) -> int:
        """The fencing epoch stamped on every leader->volume-server
        mutating rpc; 0 without raft (fencing off, single master)."""
        return self.raft.leader_epoch() if self.raft is not None else 0

    def is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader()

    def next_volume_id(self) -> int:
        """Allocate a volume id; in quorum mode the increment commits
        through raft before use (topology/cluster_commands.go)."""
        if self.raft is None:
            return self.topo.next_volume_id()
        ok, vid = self.raft.propose_and_get({"op": "inc_vid"})
        if not ok or vid is None:
            raise RuntimeError("not the leader or quorum unavailable")
        return int(vid)

    def leader(self) -> str:
        if self.raft is not None and self.raft.leader_id:
            return self.raft.leader_id
        return f"{self.ip}:{self.port}"

    def leader_grpc(self) -> str:
        host, _, port = self.leader().partition(":")
        return f"{host}:{int(port) + GRPC_PORT_OFFSET}"

    # -- layouts ----------------------------------------------------------

    def delete_collection(self, name: str) -> None:
        """Delete a collection everywhere: fan out DeleteCollection to the
        volume servers AND purge the master's own layouts, so a later
        assign to the same collection name starts from scratch instead of
        picking a deleted vid out of a stale writable set
        (master_grpc_server_collection.go)."""
        with self.topo.lock:
            nodes = list(self.topo.nodes.values())
        for n in nodes:
            try:
                rpclib.volume_server_stub(
                    n.grpc_address, timeout=30
                ).DeleteCollection(
                    vs.DeleteCollectionRequest(collection=name))
            except grpc.RpcError:
                pass
        with self._layout_lock:
            for key in [k for k in self.layouts if k[0] == name]:
                del self.layouts[key]
        with self._grow_locks_guard:
            for key in [k for k in self._grow_locks if k[0] == name]:
                del self._grow_locks[key]

    def get_layout(self, collection: str, replication: str, ttl: str) -> VolumeLayout:
        replication = replication or self.default_replication
        key = (collection, replication, ttl)
        with self._layout_lock:
            layout = self.layouts.get(key)
            if layout is None:
                layout = VolumeLayout(
                    ReplicaPlacement.parse(replication),
                    ttl,
                    self.topo.volume_size_limit,
                )
                self.layouts[key] = layout
            return layout

    def unregister_from_layouts(self, vids, node_id: str) -> None:
        with self._layout_lock:
            for layout in self.layouts.values():
                for vid in vids:
                    layout.unregister(vid, node_id)

    def rebuild_layouts(self, node) -> None:
        """Re-register a node's volumes into their layouts."""
        with self.topo.lock:
            volumes = list(node.volumes.values())
        for v in volumes:
            rp = ReplicaPlacement.from_byte(v.replica_placement)
            from ..storage.ttl import TTL

            layout = self.get_layout(
                v.collection, str(rp), str(TTL.from_uint32(v.ttl))
            )
            layout.register(v.volume_id, node.id, v.size, v.read_only)
            layout.set_oversized(v.volume_id, v.size)

    # -- assign -----------------------------------------------------------

    def sign_fid(self, fid: str) -> str:
        """Write JWT for an assigned fid (security/jwt.go GenJwt); empty
        when the cluster runs without a signing key."""
        if not self.jwt_signing_key:
            return ""
        from ..security.jwt import gen_write_jwt

        return gen_write_jwt(self.jwt_signing_key, fid)

    def assign(self, count: int, collection: str, replication: str,
               ttl: str, data_center: str = "", rack: str = "") -> tuple[str, str, str, int]:
        # instrumented HERE (not in the HTTP layer) so gRPC Assign and
        # /dir/assign both land in the same ("master","assign") series,
        # now with a latency histogram + span instead of counter-only
        with record_op("master", "assign", collection=collection):
            return self._assign(count, collection, replication, ttl,
                                data_center, rack)

    def _assign(self, count: int, collection: str, replication: str,
                ttl: str, data_center: str = "", rack: str = "") -> tuple[str, str, str, int]:
        # warm-up barrier (ISSUE 17): a freshly elected leader must not
        # hand out fids until the deposed leader's committed tail is
        # applied and a heartbeat cycle has refreshed topology — close
        # the fid-reuse window by BLOCKING briefly (clients see a slow
        # assign during failover, never a 5xx)
        if not self._warmed.wait(timeout=15.0):
            raise RuntimeError("control plane warming up after failover")
        layout = self.get_layout(collection, replication, ttl)
        try:
            vid, node_ids = layout.pick_for_write()
        except LookupError:
            # serialize growth PER LAYOUT and re-check inside the lock: a
            # burst of first assigns to a new collection would otherwise
            # each grow their own batch (observed: 5 concurrent growths
            # allocating 15 volumes where 3 suffice), while a stalled
            # grow for one collection must not block assigns elsewhere
            key = (collection, replication or self.default_replication, ttl)
            with self._grow_locks_guard:
                grow_lock = self._grow_locks.setdefault(
                    key, threading.Lock())
            with grow_lock:
                try:
                    vid, node_ids = layout.pick_for_write()
                except LookupError:
                    self.grow_volumes(
                        collection,
                        replication or self.default_replication,
                        ttl, data_center, rack)
                    vid, node_ids = layout.pick_for_write()
        key = self.sequencer.next_file_id(count)
        cookie = self._rng.randrange(0, 2**32)
        fid = f"{vid},{key:x}{cookie:08x}"
        node = self.topo.nodes.get(node_ids[0])
        url = node.id if node else node_ids[0]
        public_url = node.public_url if node else node_ids[0]
        return fid, url, public_url, count

    def grow_volumes(self, collection: str, replication: str, ttl: str,
                     data_center: str = "", rack: str = "",
                     target_count: int | None = None) -> list[int]:
        """VolumeGrowth: pick nodes per placement, AllocateVolume on each."""
        rp = ReplicaPlacement.parse(replication)
        # grow several volumes for write concurrency, like the reference's
        # automatic growth defaults (volume_growth.go)
        n_grow = target_count or max(1, 7 // rp.copy_count() // 2)
        glog.info("growing %d volume(s) collection=%r replication=%s",
                  n_grow, collection, replication)
        grown: list[int] = []
        for _ in range(n_grow):
            with self.topo.lock:
                candidates = [
                    Candidate(n.id, n.data_center, n.rack, n.free_slots())
                    for n in self.topo.nodes.values()
                ]
            try:
                picked = pick_nodes_for_write(
                    candidates, rp, data_center, rack,
                    rng=random.Random(self._rng.random()),
                )
            except ValueError:
                if grown:
                    break
                raise
            vid = self.next_volume_id()
            ok = True
            for c in picked:
                node = self.topo.nodes[c.node_id]
                try:
                    rpclib.volume_server_stub(node.grpc_address, timeout=30).AllocateVolume(
                        vs.AllocateVolumeRequest(
                            volume_id=vid,
                            collection=collection,
                            replication=replication,
                            ttl=ttl,
                        )
                    )
                except grpc.RpcError:
                    ok = False
                    break
            if ok:
                layout = self.get_layout(collection, replication, ttl)
                for c in picked:
                    layout.register(vid, c.node_id, 0, False)
                grown.append(vid)
        return grown

    def lookup_volume_locations(self, vid: int) -> list[tuple[str, str]]:
        """-> [(url, public_url)]: layouts first (fresh growth), then the
        topology (heartbeat state), then EC shard holders."""
        node_ids: list[str] = []
        with self._layout_lock:
            for layout in self.layouts.values():
                if vid in layout.locations:
                    node_ids = list(layout.locations[vid])
                    break
        out = []
        with self.topo.lock:
            if not node_ids:
                node_ids = [
                    n.id for n in self.topo.nodes.values() if vid in n.volumes
                ]
            for nid in node_ids:
                n = self.topo.nodes.get(nid)
                out.append((nid, n.public_url if n else nid))
        if not out:
            seen = {}
            for ns in self.topo.lookup_ec_shards(vid).values():
                for n in ns:
                    seen[n.id] = n.public_url
            out = sorted(seen.items())
        return out

    # -- pub/sub ----------------------------------------------------------

    def subscribe(self, q) -> None:
        with self._sub_lock:
            self._subscribers.append(q)

    def unsubscribe(self, q) -> None:
        with self._sub_lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def broadcast_location(self, node, new_vids, deleted_vids) -> None:
        loc = master_pb2.VolumeLocation(
            url=node.id,
            public_url=node.public_url,
            new_vids=sorted(set(new_vids)),
            deleted_vids=sorted(set(deleted_vids)),
            leader=self.leader(),
            data_center=node.data_center,
        )
        with self._sub_lock:
            for q in self._subscribers:
                q.put(loc)

    # -- liveness ---------------------------------------------------------

    def _liveness_loop(self) -> None:
        while not self._stop.wait(self.topo.pulse_seconds):
            for node_id in self.topo.collect_dead_nodes():
                vids = self.topo.unregister_node(node_id)
                self.unregister_from_layouts(vids, node_id)
                self.note_dead_node(node_id)
                if self.is_leader():
                    # plan AFTER the node left the topology, so the
                    # orchestrator ranks exactly the post-death shard map
                    self.mass_repair.on_node_dead(node_id)
            if self.is_leader():
                self.mass_repair.tick()

    def note_dead_node(self, node_id: str) -> None:
        """Bump the dead-node sequence the heartbeat ack carries; volume
        servers seeing a newer seq invalidate their EC holder-location
        caches eagerly (the first post-death rebuild must not plan
        against the dead holder)."""
        self.dead_node_seq += 1
        self.recent_dead_nodes = (self.recent_dead_nodes + [node_id])[-8:]
        glog.warning("node %s presumed dead (seq %d)", node_id,
                     self.dead_node_seq)

    def note_disk_health(self, node) -> None:
        """Heartbeat-ingest hook for the disk-fault plane: a low-space
        or full disk gets the lifecycle plane's emergency vacuum/tier
        treatment; a failing disk becomes a proactive-evacuation trigger
        for the mass-repair orchestrator (drain it before it dies)."""
        worst = node.worst_disk_state()
        if worst == "healthy":
            return
        if worst in ("low_space", "full"):
            try:
                self.lifecycle.note_low_space(node.id)
            except Exception as e:  # noqa: BLE001 — never fail the beat
                glog.warning("low-space reaction for %s failed: %s",
                             node.id, e)
        if worst == "failing" and self.is_leader():
            try:
                self.mass_repair.on_disk_failing(node.id)
            except Exception as e:  # noqa: BLE001
                glog.warning("evacuation trigger for %s failed: %s",
                             node.id, e)

    def note_topology_change(self, node_id: str) -> None:
        """A node JOINED (first heartbeat, incl. a rejoin after a
        death): same cache-invalidation broadcast as a death, because a
        peer's found-tier holder cache trusting the node-less map for
        its full TTL makes degraded reads fail for minutes after the
        holder is back."""
        self.dead_node_seq += 1
        glog.info("node %s joined (cache-invalidation seq %d)", node_id,
                  self.dead_node_seq)

    # -- vacuum -----------------------------------------------------------

    def vacuum(self, threshold: float | None = None) -> list[int]:
        """Leader-driven Check -> Compact -> Commit over gRPC."""
        threshold = threshold or self.garbage_threshold
        vacuumed = []
        with self.topo.lock:
            vids = sorted({vid for n in self.topo.nodes.values()
                           for vid in n.volumes})
        for vid in vids:
            if self.vacuum_volume(vid, threshold):
                vacuumed.append(vid)
        return vacuumed

    def vacuum_volume(self, vid: int,
                      threshold: float | None = None,
                      force: bool = False) -> bool:
        """Check -> Compact -> Commit one volume on every holder (the
        lifecycle controller's vacuum jobs call this directly); a failed
        phase rolls back with VacuumVolumeCleanup.  Returns True when
        the volume was compacted.

        `force=True` (the disk-fault plane's emergency vacuum) includes
        read-only volumes: a read-only-FULL volume is exactly the one
        that needs its garbage compacted away.  The volume server still
        refuses remote-tiered / mid-tier volumes, so the tier race the
        normal exemption guards against stays impossible."""
        threshold = threshold or self.garbage_threshold
        with self.topo.lock:
            nodes = [n for n in self.topo.nodes.values()
                     if vid in n.volumes]
            # sealed (read-only) volumes are exempt, like the
            # reference's vacuum: they are EC-encode/tier candidates,
            # and a compact commit racing a lifecycle tier upload would
            # swap the .dat mid-transfer
            if not force and any(n.volumes[vid].read_only for n in nodes):
                return False
        if not nodes:
            return False
        try:
            epoch = self.leader_epoch()
            ratios = [
                rpclib.volume_server_stub(n.grpc_address, timeout=30)
                .VacuumVolumeCheck(vs.VacuumVolumeCheckRequest(
                    volume_id=vid, leader_epoch=epoch))
                .garbage_ratio
                for n in nodes
            ]
            if not ratios or min(ratios) < threshold:
                return False
            for n in nodes:
                rpclib.volume_server_stub(n.grpc_address, timeout=600).VacuumVolumeCompact(
                    vs.VacuumVolumeCompactRequest(
                        volume_id=vid, leader_epoch=epoch)
                )
            for n in nodes:
                rpclib.volume_server_stub(n.grpc_address, timeout=600).VacuumVolumeCommit(
                    vs.VacuumVolumeCommitRequest(
                        volume_id=vid, leader_epoch=epoch)
                )
            return True
        except grpc.RpcError:
            for n in nodes:
                try:
                    rpclib.volume_server_stub(n.grpc_address, timeout=30).VacuumVolumeCleanup(
                        vs.VacuumVolumeCleanupRequest(
                            volume_id=vid,
                            leader_epoch=self.leader_epoch())
                    )
                except grpc.RpcError:
                    pass
            return False

    # -- maintenance loop (ec.encode/rebuild/balance automation) ----------

    def _maintenance_loop(self) -> None:
        from ..shell.commands import CommandEnv, run_maintenance

        while not self._stop.wait(self.maintenance_interval):
            try:
                # self-healing first: corruption findings queued by scrub
                # daemons turn into re-copies/rebuilds before the heavier
                # encode/balance script runs
                self.repair_pass()
            except Exception as e:
                glog.warning("repair pass failed: %s", e)
            try:
                env = CommandEnv(f"{self.ip}:{self.grpc_port}")
                for line in run_maintenance(env,
                                            script=self.maintenance_script):
                    if glog.V(1):
                        glog.info("maintenance: %s", line)
            except Exception as e:  # the loop must survive, not go mute
                glog.warning("maintenance run failed: %s", e)

    # -- self-healing: scrub finding ingest + repair orchestration --------

    MAX_SCRUB_FINDINGS = 1024
    MAX_REPAIR_ATTEMPTS = 3

    def record_scrub_findings(self, node_id: str, findings) -> None:
        """Heartbeat ingest: keep findings keyed so a node re-reporting
        persistent corruption updates in place instead of piling up."""
        with self._scrub_lock:
            for f in findings:
                key = (node_id, f.volume_id, f.kind, f.shard_id, f.needle_id)
                cur = self.scrub_findings.get(key)
                if cur is not None:
                    cur["last_reported_ms"] = f.detected_at_ms
                    continue
                if len(self.scrub_findings) >= self.MAX_SCRUB_FINDINGS:
                    # one rotten disk can report thousands of needles;
                    # the repair (one volume re-copy) fixes them all, so
                    # dropping the tail loses nothing actionable
                    continue
                self.scrub_findings[key] = {
                    "node": node_id, "volume_id": f.volume_id,
                    "kind": f.kind, "shard_id": f.shard_id,
                    "needle_id": f.needle_id, "detail": f.detail,
                    "detected_at_ms": f.detected_at_ms,
                    "last_reported_ms": f.detected_at_ms,
                    "attempts": 0, "status": "pending",
                }

    def scrub_findings_snapshot(self) -> list[dict]:
        with self._scrub_lock:
            return [dict(v) for v in self.scrub_findings.values()]

    def repair_pass(self) -> dict:
        """Turn queued scrub findings into repairs: a corrupt replica is
        re-copied from a healthy peer (VolumeCopy), a corrupt EC shard is
        deleted and rebuilt in place (VolumeEcShardsRebuild) then
        remounted.  Also refreshes the under-replication gauge."""
        summary = {"repaired": [], "failed": [], "skipped": []}
        if not self.is_leader():
            return summary
        if not self._repair_mutex.acquire(blocking=False):
            return summary  # a pass is already running (loop vs /vol/repair)
        try:
            return self._repair_pass_locked(summary)
        finally:
            # conservative: vids stay claimed for the whole pass, so the
            # mass-repair planner can never start on a volume this pass
            # is mid-VolumeCopy on
            with self._repair_claim_lock:
                self._scrub_repairing.clear()
            self._repair_mutex.release()

    def _mass_repair_active_vids(self) -> set[int]:
        """Volumes with an active mass_repair journal job: the scrub
        repair pass leaves them to the orchestrator (and vice versa —
        one repairer per volume, never a double rebuild)."""
        from ..maintenance.mass_repair import TRANSITION

        return {j["volume_id"] for j in self.lifecycle.journal.active()
                if j.get("transition") == TRANSITION}

    def _repair_pass_locked(self, summary: dict) -> dict:
        from ..stats.metrics import SCRUB_REPAIRS

        with self._scrub_lock:
            work = [(k, dict(v)) for k, v in self.scrub_findings.items()
                    if v["status"] in ("pending", "failed")
                    and v["attempts"] < self.MAX_REPAIR_ATTEMPTS]
        # claim EVERY volume this pass intends to touch UP FRONT and
        # snapshot the orchestrator's active jobs in the same locked
        # section: the mass-repair planner journals its jobs under this
        # lock while reading our claims, so a node death arriving
        # mid-pass can never interleave check-then-act on one volume
        with self._repair_claim_lock:
            self._scrub_repairing.update(f["volume_id"] for _k, f in work)
            mass_busy = self._mass_repair_active_vids()
        for key, f in work:
            with self._scrub_lock:
                if key not in self.scrub_findings:
                    # an earlier repair in THIS pass already healed the
                    # whole volume and dropped its sibling findings
                    continue
            if f["volume_id"] in mass_busy:
                # the mass-repair orchestrator is rebuilding this volume
                # right now; the finding stays queued and a later pass
                # re-checks it against the freshly rebuilt shards
                summary["skipped"].append(key)
                continue
            kind = f["kind"]
            repair_kind = "ec_shard" if kind == "ec_shard" else "replica"
            try:
                if kind == "ec_shard":
                    self._repair_ec_shard(f)
                else:
                    # replica + index findings both heal by re-copying the
                    # whole volume from a healthy peer
                    self._repair_replica(f)
            except _Unrepairable as e:
                with self._scrub_lock:
                    if key in self.scrub_findings:
                        self.scrub_findings[key]["status"] = "unrepairable"
                        self.scrub_findings[key]["error"] = str(e)
                summary["skipped"].append(key)
                continue
            except Exception as e:  # noqa: BLE001 — per-finding isolation
                SCRUB_REPAIRS.labels(repair_kind, "error").inc()
                with self._scrub_lock:
                    if key in self.scrub_findings:
                        self.scrub_findings[key]["attempts"] += 1
                        self.scrub_findings[key]["status"] = "failed"
                        self.scrub_findings[key]["error"] = str(e)
                glog.warning("repair of %s failed: %s", key, e)
                summary["failed"].append(key)
                continue
            SCRUB_REPAIRS.labels(repair_kind, "ok").inc()
            with self._scrub_lock:
                if kind == "ec_shard":
                    # the rebuild healed exactly this shard
                    drop = [k for k, v in self.scrub_findings.items()
                            if v["node"] == f["node"]
                            and v["volume_id"] == f["volume_id"]
                            and v["kind"] == "ec_shard"
                            and v["shard_id"] == f["shard_id"]]
                else:
                    # one volume re-copy heals EVERY queued needle/index
                    # finding on that (node, volume)
                    drop = [k for k, v in self.scrub_findings.items()
                            if v["node"] == f["node"]
                            and v["volume_id"] == f["volume_id"]
                            and v["kind"] != "ec_shard"]
                for k in drop:
                    del self.scrub_findings[k]
            glog.info("repaired %s finding on %s vol=%d",
                      kind, f["node"], f["volume_id"])
            summary["repaired"].append(key)
        self.update_replication_health()
        return summary

    def _repair_replica(self, f: dict) -> None:
        """Re-copy a corrupted replica from a healthy peer via the
        existing VolumeCopy pull protocol."""
        vid = f["volume_id"]
        with self.topo.lock:
            corrupt = self.topo.nodes.get(f["node"])
            holders = [n for n in self.topo.nodes.values()
                       if vid in n.volumes]
            collection = ""
            for n in holders:
                collection = n.volumes[vid].collection
                break
        if corrupt is None:
            raise _Unrepairable(f"node {f['node']} left the cluster")
        healthy = [n for n in holders if n.id != corrupt.id]
        if not healthy:
            raise _Unrepairable(
                f"volume {vid}: no healthy replica to copy from")
        source = healthy[0]
        stub = rpclib.volume_server_stub(corrupt.grpc_address, timeout=600)
        try:
            stub.VolumeUnmount(vs.VolumeUnmountRequest(volume_id=vid))
        except grpc.RpcError:
            pass  # already unmounted (or racing) — the copy re-mounts
        stub.VolumeCopy(vs.VolumeCopyRequest(
            volume_id=vid, collection=collection,
            source_data_node=source.grpc_address,
        ))

    def _repair_ec_shard(self, f: dict) -> None:
        """Rebuild a corrupted EC shard in place: drop the rotten .ecNN,
        decode it back from the surviving shards, remount."""
        vid, sid = f["volume_id"], f["shard_id"]
        with self.topo.lock:
            node = self.topo.nodes.get(f["node"])
            collection = (node.ec_collections.get(vid, "")
                          if node is not None else "")
        if node is None:
            raise _Unrepairable(f"node {f['node']} left the cluster")
        stub = rpclib.volume_server_stub(node.grpc_address, timeout=600)
        stub.VolumeEcShardsDelete(vs.VolumeEcShardsDeleteRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]))
        rebuilt = stub.VolumeEcShardsRebuild(vs.VolumeEcShardsRebuildRequest(
            volume_id=vid, collection=collection))
        if sid not in list(rebuilt.rebuilt_shard_ids):
            raise IOError(
                f"shard {sid} not rebuilt (got {list(rebuilt.rebuilt_shard_ids)})")
        stub.VolumeEcShardsMount(vs.VolumeEcShardsMountRequest(
            volume_id=vid, collection=collection, shard_ids=[sid]))

    def update_replication_health(self) -> dict:
        """Per-volume replica health + the cluster under-replication
        gauge (seaweedfs_volume_underreplicated)."""
        from ..stats.metrics import VOLUME_UNDERREPLICATED

        health: dict[str, dict] = {}
        under = 0
        with self.topo.lock:
            holders: dict[int, list] = {}
            desired: dict[int, int] = {}
            for n in self.topo.nodes.values():
                for vid, v in n.volumes.items():
                    holders.setdefault(vid, []).append(n.id)
                    desired[vid] = ReplicaPlacement.from_byte(
                        v.replica_placement).copy_count()
        for vid, locs in holders.items():
            want = max(desired.get(vid, 1), 1)
            if len(locs) < want:
                under += 1
                health[str(vid)] = {
                    "replicas": len(locs), "desired": want,
                    "underReplicated": True, "locations": sorted(locs),
                }
        VOLUME_UNDERREPLICATED.set(under)
        self._volume_health = health
        return health

    def volume_health_snapshot(self) -> dict:
        """The /cluster/status health block: under-replicated volumes +
        outstanding scrub findings grouped per volume."""
        health = dict(getattr(self, "_volume_health", {}))
        for f in self.scrub_findings_snapshot():
            entry = health.setdefault(str(f["volume_id"]), {})
            entry.setdefault("findings", []).append({
                "node": f["node"], "kind": f["kind"],
                "shardId": f["shard_id"],
                "needleId": f"{f['needle_id']:x}",
                "status": f["status"], "attempts": f["attempts"],
                "detail": f.get("detail", ""),
            })
        return health

    # -- admin lock -------------------------------------------------------

    def lease_admin_token(self, lock_name: str, previous: int) -> int | None:
        with self._admin_lock_mutex:
            current = self._admin_locks.get(lock_name)
            if current is not None and current != previous:
                return None
            token = int(time.time_ns())
            self._admin_locks[lock_name] = token
            return token

    def release_admin_token(self, lock_name: str, token: int) -> None:
        with self._admin_lock_mutex:
            if self._admin_locks.get(lock_name) == token:
                del self._admin_locks[lock_name]

    # -- observability plane ----------------------------------------------

    MAX_STATS_SNAPSHOTS = 256

    def record_stats_snapshot(self, instance: str, node_type: str,
                              snapshot) -> None:
        """Keep a node's heartbeat stats snapshot (pb StatsSnapshot) as
        the /cluster/metrics fallback when a live scrape can't reach it.
        Survives the node leaving the topology — that is the whole point."""
        if not snapshot.samples:
            return
        with self._snapshots_lock:
            # pop-then-reinsert keeps the dict ordered by receive time,
            # so the bound evicts the stalest entry in O(1) — this runs
            # on every full heartbeat of every volume server
            self.stats_snapshots.pop(instance, None)
            self.stats_snapshots[instance] = {
                "type": node_type,
                "samples": [(s.name, s.value) for s in snapshot.samples],
                "captured_at_ms": snapshot.captured_at_ms,
                "received": time.monotonic(),
            }
            if len(self.stats_snapshots) > self.MAX_STATS_SNAPSHOTS:
                del self.stats_snapshots[next(iter(self.stats_snapshots))]

    def stats_snapshots_snapshot(self) -> dict:
        with self._snapshots_lock:
            return dict(self.stats_snapshots)

    def register_client(self, name: str, client_type: str,
                        http_address: str) -> object:
        """-> registration token.  Unregistration requires the token: a
        reconnecting client registers on its new stream BEFORE the old
        stream's handler notices the break (up to its poll interval), so
        an unconditional pop would deregister the fresh registration and
        the client would vanish from the federation plane until its next
        reconnect."""
        token = object()
        with self._clients_lock:
            self.clients[name] = {
                "type": client_type,
                "http_address": http_address,
                "last_seen": time.monotonic(),
                "token": token,
            }
        return token

    def touch_client(self, name: str) -> None:
        with self._clients_lock:
            info = self.clients.get(name)
            if info is not None:
                info["last_seen"] = time.monotonic()

    def unregister_client(self, name: str, token: object) -> None:
        with self._clients_lock:
            info = self.clients.get(name)
            if info is not None and info["token"] is token:
                del self.clients[name]

    def clients_snapshot(self) -> dict:
        with self._clients_lock:
            return {k: dict(v) for k, v in self.clients.items()}

    # -- geo registry (ISSUE 12) ------------------------------------------

    def geo_status(self) -> dict:
        """The /cluster/geo document: peer-cluster reachability (probed
        live, concurrently, 1s each) plus every geo link sample the
        filers' heartbeat snapshots carried (lag, shipped/applied/
        conflict counters) — the operator's one-stop geo view."""
        from ..util import connpool

        def probe(addr: str) -> dict:
            try:
                with connpool.request(
                        "GET", f"http://{addr}/cluster/status",
                        timeout=2) as r:
                    doc = json.loads(r.read())
                return {
                    "reachable": True,
                    "leader": doc.get("Leader", ""),
                    "dataNodes": len(doc.get("DataNodes") or {}),
                    "filers": len(doc.get("Filers") or {}),
                }
            except Exception as e:  # noqa: BLE001 — a dead peer is data
                return {"reachable": False, "error": str(e)[:200]}

        peers = {}
        if self.peer_clusters:
            futures = {
                addr: self.federation_pool.submit(probe, addr)
                for addr in self.peer_clusters
            }
            peers = {addr: fut.result() for addr, fut in futures.items()}
        links: dict[str, dict] = {}
        for instance, snap in self.stats_snapshots_snapshot().items():
            geo = {name: value for name, value in snap.get("samples", [])
                   if name.startswith("seaweedfs_geo_")
                   or name.startswith("seaweedfs_meta_listener_")}
            if geo:
                links[instance] = geo
        return {"peerClusters": peers, "links": links}


# ---------------------------------------------------------------------------
# HTTP API (/dir/assign, /dir/lookup, /cluster/status, /vol/vacuum)
# ---------------------------------------------------------------------------


# request-metric op per path; unknown paths collapse to "other" so a
# scanner can't explode the label cardinality.  /dir/assign is absent
# on purpose: the logical ("master","assign") series inside
# MasterServer.assign() covers it (shared with the gRPC path), and a
# second middleware series for the same request would double-count
# master QPS.
_MASTER_OPS = {
    "/dir/lookup": "dir.lookup",
    "/dir/status": "cluster.status", "/cluster/status": "cluster.status",
    "/cluster/healthz": "cluster.healthz", "/stats/health": "cluster.healthz",
    "/cluster/raft": "cluster.raft",
    "/cluster/metrics": "cluster.metrics",
    "/cluster/traces": "cluster.traces",
    "/cluster/alerts": "cluster.alerts",
    "/cluster/lifecycle": "cluster.lifecycle",
    "/cluster/geo": "cluster.geo",
    "/cluster/hot": "cluster.hot",
    "/cluster/debug": "cluster.debug",
    "/cluster/debug/capture": "cluster.debug",
    "/debug/hot": "debug.hot",
    "/debug/profile/history": "debug.profile",
    "/vol/vacuum": "vol.vacuum", "/vol/grow": "vol.grow",
    "/vol/repair": "vol.repair",
    "/vol/status": "vol.status", "/col/delete": "col.delete",
    "/submit": "submit", "/debug/profile": "debug.profile",
    "/debug/traces": "debug.traces", "/metrics": "metrics",
    "/ui": "ui", "/ui/": "ui", "/ui/index.html": "ui",
}


def _master_op(path: str) -> str:
    return _MASTER_OPS.get(path.split("?")[0], "other")


class _MasterHttpHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    master: MasterServer = None

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj: dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _redirect_to_leader(self) -> None:
        """307 to the leader; 503 when no leader is elected.  Drains any
        unread request body first — skipping it desyncs HTTP/1.1
        keep-alive (the next request parses the stale body as a request
        line)."""
        self._drain_body()
        leader = self.master.leader()
        if leader == f"{self.master.ip}:{self.master.port}":
            return self._json(503, {"error": "no leader elected yet"})
        self.send_response(307)
        self.send_header("Location", f"http://{leader}{self.path}")
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        with http_request(self, "master", _master_op(self.path)):
            self._do_delete()

    def _do_delete(self):
        u = urllib.parse.urlparse(self.path)
        if u.path == "/col/delete":
            return self._col_delete(u)
        return self._json(404, {"error": f"unknown path {u.path}"})

    def _col_delete(self, u) -> None:
        # master_server_handlers_admin.go deleteFromMasterServerHandler.
        # Exactly ONE drain per request: _redirect_to_leader drains for
        # itself, so the leader/error paths drain here and the redirect
        # path must not (draining twice blocks on already-consumed bytes)
        q = urllib.parse.parse_qs(u.query)
        name = q.get("collection", [""])[0]
        if not name:
            self._drain_body()
            return self._json(400, {"error": "collection required"})
        if not self.master.is_leader():
            return self._redirect_to_leader()
        self._drain_body()  # keep-alive hygiene: params ride the query
        self.master.delete_collection(name)
        return self._json(200, {"collection": name, "deleted": True})

    def _drain_body(self, cap: int = 1 << 20) -> None:
        from ..util.httpd import drain_request_body

        drain_request_body(self, cap)

    def do_POST(self):
        with http_request(self, "master", _master_op(self.path)):
            self._do_post()

    def _do_post(self):
        u = urllib.parse.urlparse(self.path)
        if u.path == "/col/delete":
            return self._col_delete(u)
        if u.path == "/cluster/raft" and self.master.raft is not None:
            length = int(self.headers.get("Content-Length") or 0)
            payload = self.rfile.read(length)
            if not self.master.verify_raft_request(
                payload, self.headers.get("X-Raft-Signature", "")
            ):
                return self._json(403, {"error": "bad raft signature"})
            try:
                msg = json.loads(payload)
                return self._json(200, self.master.raft.handle(msg))
            except (ValueError, KeyError) as e:
                return self._json(400, {"error": str(e)})
        if u.path == "/submit":
            # one-shot convenience: assign + upload in a single request
            # (master_server_handlers.go submitFromMasterServerHandler)
            from ..operation.upload import upload_data
            from ..volume.http_handlers import _parse_multipart

            if not self.master.is_leader():
                return self._redirect_to_leader()
            q = urllib.parse.parse_qs(u.query)
            try:
                length = int(self.headers.get("Content-Length") or 0)
                # the master never handles object payloads elsewhere — cap
                # /submit bodies so one oversized POST can't exhaust its
                # memory (413 mirrors the volume server's own size check).
                # Draining a >limit body is impractical, so the keep-alive
                # connection closes instead of desyncing on the unread rest
                if length > self.master.topo.volume_size_limit:
                    self.close_connection = True
                    return self._json(413, {
                        "error": "submitted object exceeds volume size limit"})
                body = self.rfile.read(length)
                ctype = self.headers.get("Content-Type", "")
                name = mime = b""
                if ctype.startswith("multipart/form-data"):
                    data, name, mime = _parse_multipart(body, ctype)
                else:
                    data = body
                fid, url, public_url, _count = self.master.assign(
                    count=1,
                    collection=q.get("collection", [""])[0],
                    replication=q.get("replication", [""])[0],
                    ttl=q.get("ttl", [""])[0],
                    data_center=q.get("dataCenter", [""])[0],
                    rack=q.get("rack", [""])[0],
                )
                res = upload_data(
                    f"http://{url}/{fid}", data,
                    filename=name.decode() if name else "",
                    mime=mime.decode() if mime else "",
                    jwt=self.master.sign_fid(fid),
                )
                return self._json(201, {
                    "fid": fid,
                    "fileUrl": f"{public_url}/{fid}",
                    "fileName": name.decode() if name else "",
                    "size": res.size,
                })
            except ValueError as e:  # malformed client input -> 400
                return self._json(400, {"error": str(e)})
            except Exception as e:
                return self._json(500, {"error": str(e)})
        return self._json(404, {"error": f"unknown path {u.path}"})

    def do_GET(self):
        from ..telemetry import trace

        if self.path.split("?")[0] == "/dir/assign":
            # metered once, inside MasterServer.assign(); here only the
            # caller's trace context is adopted so the assign span joins
            with trace.remote_context(self.headers.get(trace.TRACEPARENT)):
                return self._do_get()
        with http_request(self, "master", _master_op(self.path)):
            self._do_get()

    def _do_get(self):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)

        def qget(name, default=""):
            return q.get(name, [default])[0]

        if serve_debug_http(self, u.path):
            return

        if u.path == "/cluster/metrics":
            from ..stats.metrics import parse_family_prefixes
            from . import observability

            try:
                prefixes = parse_family_prefixes(qget("family"))
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            body = observability.cluster_metrics(
                self.master, prefixes).encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if u.path == "/cluster/alerts":
            # the judgment plane's operator surface: SLO states, active
            # alerts (exemplar trace ids included), bounded transition
            # history, the canary's last probe round, and the flight
            # recorder's captured bundles (the page's evidence locker)
            doc = self.master.slo.status()
            doc["canary"] = self.master.canary.status()
            doc["debugBundles"] = self.master.flight.list_bundles()
            return self._json(200, doc)
        if u.path == "/cluster/hot":
            # federated heavy-hitter tables: which needle/bucket/tenant/
            # peer is hot right now, cluster-wide, in one request
            from . import observability

            try:
                n = int(qget("n", "32") or 32)
                if not 1 <= n <= 1024:
                    raise ValueError
            except ValueError:
                return self._json(400, {"error": "n must be in [1, 1024]"})
            return self._json(200, observability.cluster_hot(
                self.master, n))
        if u.path == "/cluster/debug":
            name = qget("bundle")
            if name:
                doc = self.master.flight.bundle(name)
                if doc is None:
                    return self._json(404, {
                        "error": f"no bundle named {name!r}"})
                return self._json(200, doc)
            return self._json(200, {
                "debugDir": self.master.flight.debug_dir,
                "retain": self.master.flight.retain,
                "bundles": self.master.flight.list_bundles(),
            })
        if u.path == "/cluster/debug/capture":
            # on-demand flight-recorder capture (the shell's
            # cluster.debug -capture); alert-triggered captures run
            # through the SLO sink without this endpoint
            try:
                return self._json(200, self.master.flight.capture(
                    trigger="manual"))
            except RuntimeError as e:  # capture already in flight
                return self._json(409, {"error": str(e)})
            except Exception as e:
                return self._json(500, {"error": str(e)})
        if u.path == "/cluster/lifecycle":
            # lifecycle controller status: policies, journal, job states
            return self._json(200, self.master.lifecycle.status())
        if u.path == "/cluster/geo":
            # peer-cluster registry + per-link replication health
            return self._json(200, self.master.geo_status())
        if u.path == "/cluster/traces":
            from ..telemetry import parse_trace_query
            from . import observability

            try:
                trace_id, limit = parse_trace_query(q)
            except ValueError as e:
                return self._json(400, {"error": str(e)})
            if trace_id is None:
                return self._json(400, {
                    "error": "trace=<32-hex trace id> is required "
                             "(per-node rings are at /debug/traces)"})
            return self._json(200, observability.cluster_traces(
                self.master, trace_id, limit))

        if (((u.path.startswith("/dir/") and u.path != "/dir/status")
                or u.path in ("/vol/grow", "/vol/status"))
                and not self.master.is_leader()):
            # followers hold no topology (volume servers heartbeat the
            # leader only) — redirect like the reference's ProxyToLeader
            return self._redirect_to_leader()
        if u.path == "/dir/assign":
            try:
                fid, url, public_url, count = self.master.assign(
                    count=int(qget("count", "1") or 1),
                    collection=qget("collection"),
                    replication=qget("replication"),
                    ttl=qget("ttl"),
                    data_center=qget("dataCenter"),
                    rack=qget("rack"),
                )
                out = {
                    "fid": fid, "url": url, "publicUrl": public_url,
                    "count": count,
                }
                auth = self.master.sign_fid(fid)
                if auth:
                    out["auth"] = auth
                return self._json(200, out)
            except Exception as e:
                return self._json(500, {"error": str(e)})
        if u.path == "/dir/lookup":
            vid_s = qget("volumeId") or qget("fileId").split(",")[0]
            try:
                vid = int(vid_s)
            except ValueError:
                return self._json(400, {"error": "invalid volumeId"})
            locations = self.master.lookup_volume_locations(vid)
            if not locations:
                return self._json(404, {"volumeId": vid_s, "error": "not found"})
            return self._json(200, {
                "volumeId": vid_s,
                "locations": [
                    {"url": url, "publicUrl": public_url}
                    for url, public_url in locations
                ],
            })
        if u.path in ("/ui", "/ui/", "/ui/index.html"):
            from ..util.ui import render_status_page

            with self.master.topo.lock:
                page = render_status_page(
                    f"seaweedfs-tpu master {self.master.ip}:{self.master.port}",
                    {
                        "Cluster": {
                            "IsLeader": self.master.is_leader(),
                            "Leader": self.master.leader(),
                            "MaxVolumeId": self.master.topo.max_volume_id,
                        },
                        "DataNodes": [
                            {
                                "id": n.id,
                                "dataCenter": n.data_center,
                                "rack": n.rack,
                                "volumes": len(n.volumes),
                                "ecVolumes": len(n.ec_shards),
                            }
                            for n in self.master.topo.nodes.values()
                        ],
                    })
            self.send_response(200)
            self.send_header("Content-Type", "text/html")
            self.send_header("Content-Length", str(len(page)))
            self.end_headers()
            self.wfile.write(page)
            return
        if u.path in ("/cluster/status", "/dir/status"):
            from . import observability

            return self._json(200, observability.cluster_status(self.master))
        if u.path == "/vol/vacuum":
            vacuumed = self.master.vacuum(
                float(qget("garbageThreshold", "0") or 0) or None
            )
            return self._json(200, {"vacuumed": vacuumed})
        if u.path == "/vol/repair":
            # on-demand repair pass over queued scrub findings (the
            # maintenance loop runs the same pass on its interval)
            if not self.master.is_leader():
                return self._redirect_to_leader()
            s = self.master.repair_pass()
            return self._json(200, {
                "repaired": [list(k) for k in s["repaired"]],
                "failed": [list(k) for k in s["failed"]],
                "skipped": [list(k) for k in s["skipped"]],
                "outstanding": len(self.master.scrub_findings_snapshot()),
                "massRepair": self.master.mass_repair.status(),
            })
        if u.path == "/vol/grow":
            # master_server_handlers_admin.go volumeGrowHandler
            try:
                grown = self.master.grow_volumes(
                    qget("collection"),
                    qget("replication") or self.master.default_replication,
                    qget("ttl"),
                    data_center=qget("dataCenter"),
                    rack=qget("rack"),
                    target_count=int(qget("count", "0") or 0) or None,
                )
                return self._json(200, {"count": len(grown),
                                        "volumeIds": grown})
            except ValueError as e:  # malformed client input -> 400
                return self._json(400, {"error": str(e)})
            except Exception as e:
                return self._json(500, {"error": str(e)})
        if u.path == "/vol/status":
            with self.master.topo.lock:
                vols = {}
                for n in self.master.topo.nodes.values():
                    for vid, v in n.volumes.items():
                        vols.setdefault(str(vid), {
                            "size": v.size,
                            "fileCount": v.file_count,
                            "collection": v.collection,
                            "readOnly": v.read_only,
                            "replicaPlacement": str(
                                ReplicaPlacement.from_byte(
                                    v.replica_placement)),
                            "locations": [],
                        })["locations"].append(n.id)
                return self._json(200, {"Volumes": vols})
        if u.path == "/col/delete":
            # state-changing: POST/DELETE only, so a stray crawler's GET
            # can't drop a collection
            return self._json(405, {
                "error": "collection delete requires POST or DELETE"})
        if u.path in ("/cluster/healthz", "/stats/health"):
            own = f"{self.master.ip}:{self.master.port}"
            healthy = (self.master.is_leader()
                       or self.master.leader() != own)
            return self._json(200 if healthy else 503, {"ok": healthy})
        return self._json(404, {"error": f"unknown path {u.path}"})


def _serve_http(master: MasterServer, host: str, port: int) -> ThreadingHTTPServer:
    handler = type("BoundMasterHttp", (_MasterHttpHandler,), {"master": master})
    httpd = FrameworkHTTPServer((host, port), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd
