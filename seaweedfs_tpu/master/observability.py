"""Master-side cluster observability plane: federated /cluster/metrics,
stitched /cluster/traces, and the /cluster/status JSON.

The master is the only process that knows every node (volume servers
heartbeat it, filers register over KeepConnected), so it is the natural
single pane: scrape fan-out runs here over the shared keep-alive pool
with a hard per-node deadline, and nodes that do not answer are served
from the stats snapshot their last heartbeat carried instead of
disappearing from dashboards mid-incident — exactly when they matter.
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import time

from ..stats.metrics import REGISTRY
from ..telemetry import trace
from ..telemetry.federation import FederatedExposition
from ..telemetry.stitch import estimate_skew, stitch_trace
from ..util import connpool, glog

# per-node scrape deadline: one wedged node must cost the whole
# federation render at most this, and the fan-out is concurrent so the
# total is ~max, not sum
FEDERATION_TIMEOUT_S = float(
    os.environ.get("SEAWEEDFS_TPU_FEDERATION_TIMEOUT_S", "1.0"))

# heartbeat snapshots older than this stop being served for nodes that
# left the topology — a node gone for 15 minutes is an outage, not a
# scrape blip, and its last counters would only mislead
SNAPSHOT_RETENTION_S = 900.0


def _self_target(master) -> dict:
    return {"instance": f"{master.ip}:{master.port}", "type": "master"}


def federation_targets(master) -> list[dict]:
    """Every scrapeable node the master knows: volume servers from the
    topology, filers from KeepConnected registrations, plus recently
    departed nodes that still have a fresh heartbeat snapshot (so a node
    the liveness sweep just dropped shows up stale, not vanished)."""
    targets: list[dict] = []
    seen: set[str] = set()
    with master.topo.lock:
        for n in master.topo.nodes.values():
            targets.append({"instance": n.id, "type": "volume",
                            "http_address": n.id})
            seen.add(n.id)
    for name, info in master.clients_snapshot().items():
        addr = info.get("http_address")
        if addr and addr not in seen:
            targets.append({"instance": addr, "type": info["type"],
                            "http_address": addr, "client_name": name})
            seen.add(addr)
    now = time.monotonic()
    for instance, snap in master.stats_snapshots_snapshot().items():
        if instance in seen:
            continue
        if now - snap["received"] <= SNAPSHOT_RETENTION_S:
            targets.append({"instance": instance, "type": snap["type"],
                            "http_address": instance})
            seen.add(instance)
    targets.sort(key=lambda t: (t["type"], t["instance"]))
    return targets


def _scrape(url: str, timeout: float) -> str:
    """GET with a WALL-CLOCK bound, not just a per-recv timeout: a node
    dripping one byte per recv-window would reset a socket timeout on
    every byte and wedge the fan-out worker forever."""
    deadline = time.monotonic() + timeout
    with connpool.request("GET", url, timeout=timeout) as r:
        chunks: list[bytes] = []
        while True:
            if time.monotonic() > deadline:
                raise TimeoutError(f"scrape of {url} exceeded {timeout}s")
            chunk = r.read(1 << 16)
            if not chunk:
                break
            chunks.append(chunk)
    return b"".join(chunks).decode("utf-8", errors="replace")


def cluster_metrics(master, family_prefixes: "list[str] | None" = None) -> str:
    """Prometheus exposition federated across every known node.

    `family_prefixes` (the validated ?family= filter) restricts the
    merge to matching families AND rides the per-node scrape URL, so an
    SLO evaluation tick moves a few families' worth of text per node
    instead of the full exposition."""
    fed = FederatedExposition(family_prefixes)
    t0 = time.perf_counter()
    fed.add_live(_self_target(master), REGISTRY.render(family_prefixes),
                 time.perf_counter() - t0)
    targets = federation_targets(master)
    family_q = ("?family=" + ",".join(family_prefixes)
                if family_prefixes else "")

    def scrape_one(t: dict):
        t1 = time.perf_counter()
        try:
            text = _scrape(f"http://{t['http_address']}/metrics{family_q}",
                           FEDERATION_TIMEOUT_S)
            return ("live", text, time.perf_counter() - t1)
        except Exception as e:  # noqa: BLE001 — any failure -> snapshot
            return ("down", str(e), time.perf_counter() - t1)

    futures = [(t, master.federation_pool.submit(scrape_one, t))
               for t in targets]
    snapshots = master.stats_snapshots_snapshot()
    now = time.monotonic()
    # total wall bound: scrapes run concurrently but the pool is finite
    # (and shared with /cluster/traces), so targets past the width queue
    # — the render is bounded by ~deadline x ceil(targets/width) + slack,
    # and any straggler past that is served from its snapshot like an
    # unreachable node.  Width comes from the pool itself, doubled as
    # slack for a concurrent /cluster/traces occupying slots (its
    # fetches are _scrape-wall-bounded, so slots free within ~deadline).
    width = max(1, master.federation_pool._max_workers)
    rounds = 1 + (len(targets) - 1) // width if targets else 1
    budget = FEDERATION_TIMEOUT_S * rounds * 2 + 2.0
    render_deadline = now + budget
    for t, fut in futures:
        try:
            kind, payload, dt = fut.result(
                timeout=max(0.0, render_deadline - time.monotonic()))
        except concurrent.futures.TimeoutError:
            # (not builtin TimeoutError until py3.11)
            kind, payload, dt = "down", "render budget exhausted", 0.0
        if kind == "live":
            fed.add_live(t, payload, dt)
            continue
        snap = snapshots.get(t["instance"])
        if snap is not None:
            fed.add_snapshot(t, snap["samples"], now - snap["received"])
        else:
            fed.add_down(t)
        if glog.V(1):
            glog.info("federation: %s unreachable (%s), %s",
                      t["instance"], payload,
                      "served snapshot" if snap else "no snapshot")
    return fed.render()


def cluster_traces(master, trace_id: str, limit: int) -> dict:
    """Fan /debug/traces?trace=<id> out to every node and stitch the
    per-node span lists into one parent-linked, skew-annotated timeline."""
    results = [{
        "instance": f"{master.ip}:{master.port}", "type": "master",
        "spans": _own_spans(trace_id, limit), "skew_s": 0.0, "rtt_s": 0.0,
    }]

    def fetch_one(t: dict):
        url = (f"http://{t['http_address']}/debug/traces"
               f"?trace={trace_id}&limit={limit}")
        sent_at = time.time()
        t1 = time.perf_counter()
        try:
            doc = json.loads(_scrape(url, FEDERATION_TIMEOUT_S))
        except Exception:  # noqa: BLE001 — absent node: no spans
            return None
        rtt = time.perf_counter() - t1
        spans = []
        for tr in doc.get("traces", ()):
            if tr.get("traceId") == trace_id:
                spans.extend(tr.get("spans", ()))
        skew = 0.0
        if isinstance(doc.get("now"), (int, float)):
            skew = estimate_skew(doc["now"], sent_at, rtt)
        return {"instance": t["instance"], "type": t["type"],
                "spans": spans, "skew_s": skew, "rtt_s": rtt}

    targets = federation_targets(master)
    futures = [master.federation_pool.submit(fetch_one, t) for t in targets]
    for fut in futures:
        res = fut.result()
        if res is not None:
            results.append(res)
    return stitch_trace(trace_id, results)


def cluster_hot(master, n: int = 32) -> dict:
    """Fan /debug/hot out to every node and merge the per-dimension
    sketch tables into cluster-wide ones.

    Space-saving sketches merge by summing per-key counts (and error
    bounds), so the cluster table keeps the sketch's guarantee: a key
    hot anywhere is present, with its worst-case overestimate stated."""
    from ..telemetry import hotkeys as _hotkeys

    per_node: dict[str, dict] = {
        f"{master.ip}:{master.port}": _hotkeys.snapshot(n)}

    def fetch_one(t: dict):
        try:
            return t["instance"], json.loads(_scrape(
                f"http://{t['http_address']}/debug/hot?n={n}",
                FEDERATION_TIMEOUT_S))
        except Exception as e:  # noqa: BLE001 — a dead node still lists
            return t["instance"], {"error": str(e)}

    targets = federation_targets(master)
    futures = [master.federation_pool.submit(fetch_one, t) for t in targets]
    for fut in futures:
        instance, doc = fut.result()
        per_node.setdefault(instance, doc)

    def merge(which: str) -> dict:
        tables: dict[str, dict[str, dict]] = {}
        for instance, doc in per_node.items():
            for dim, windows in (doc.get("dims") or {}).items():
                table = tables.setdefault(dim, {})
                for e in windows.get(which) or ():
                    slot = table.setdefault(e["key"], {
                        "key": e["key"], "count": 0, "error": 0,
                        "nodes": []})
                    slot["count"] += e.get("count", 0)
                    slot["error"] += e.get("error", 0)
                    slot["nodes"].append(instance)
        return {
            dim: sorted(t.values(),
                        key=lambda s: (-s["count"], s["key"]))[:n]
            for dim, t in tables.items()
        }

    current, previous = merge("current"), merge("previous")
    return {
        "nodes": {
            instance: ({"error": doc["error"]} if "error" in doc
                       else {"windowAgeS": doc.get("windowAgeS"),
                             "enabled": doc.get("enabled", True)})
            for instance, doc in sorted(per_node.items())
        },
        "dims": {
            dim: {"current": current.get(dim, []),
                  "previous": previous.get(dim, [])}
            for dim in sorted(set(current) | set(previous))
        },
    }


def _own_spans(trace_id: str, limit: int) -> list[dict]:
    for tr in trace.TRACER.recent_traces(limit, trace_id=trace_id):
        if tr["traceId"] == trace_id:
            return tr["spans"]
    return []


def cluster_status(master) -> dict:
    """The /cluster/status JSON the shell and UI consume: topology plus
    per-node liveness and federation/snapshot state."""
    now_mono = time.monotonic()
    with master.topo.lock:
        data_nodes = {
            n.id: {
                "publicUrl": n.public_url,
                "volumes": sorted(n.volumes),
                "ecShards": {
                    str(vid): bits.shard_ids()
                    for vid, bits in n.ec_shards.items()
                },
                "dataCenter": n.data_center,
                "rack": n.rack,
                "secondsSinceLastBeat": round(now_mono - n.last_seen, 1),
                # disk-fault plane: per-dir watermark state + free bytes
                # from the node's heartbeat (empty = legacy/unknown)
                "disks": {
                    d: {"state": info.get("state", "healthy"),
                        "freeBytes": info.get("free_bytes", 0),
                        "totalBytes": info.get("total_bytes", 0)}
                    for d, info in n.disk_health.items()
                },
                "diskState": n.worst_disk_state(),
            }
            for n in master.topo.nodes.values()
        }
        out = {
            "IsLeader": master.is_leader(),
            "Leader": master.leader(),
            "MaxVolumeId": master.topo.max_volume_id,
            "DataNodes": data_nodes,
        }
    out["Filers"] = {
        name: {
            "httpAddress": info.get("http_address", ""),
            "secondsSinceLastSeen": round(
                now_mono - info["last_seen"], 1),
        }
        for name, info in master.clients_snapshot().items()
    }
    out["StatsSnapshots"] = {
        instance: {
            "type": snap["type"],
            "samples": len(snap["samples"]),
            "ageSeconds": round(now_mono - snap["received"], 1),
        }
        for instance, snap in master.stats_snapshots_snapshot().items()
    }
    # self-healing plane: per-volume health (under-replication + open
    # scrub findings) so `cluster.status -json` answers "is anything
    # silently rotten and is repair keeping up"
    master.update_replication_health()
    out["VolumeHealth"] = master.volume_health_snapshot()
    out["ScrubFindings"] = len(master.scrub_findings_snapshot())
    # lifecycle plane: one-line controller summary (the full journal is
    # at /cluster/lifecycle); answers "is background maintenance alive
    # and is anything parked waiting for an operator"
    lc = master.lifecycle
    out["Lifecycle"] = {
        "enabled": lc.interval_s > 0,
        "rateMBps": lc.rate_mbps,
        "jobStates": lc.journal.counts(),
    }
    # judgment plane (ISSUE 13): is the cluster meeting its SLOs right
    # now, and are the black-box canaries proving end-to-end service —
    # the one-line health verdict cluster.status renders first
    health: dict = {}
    slo = getattr(master, "slo", None)
    if slo is not None:
        health["slo"] = slo.health_summary()
    canary = getattr(master, "canary", None)
    if canary is not None:
        cs = canary.status()
        health["canary"] = {
            "running": cs["running"],
            "tick": cs["tick"],
            "byteMismatches": cs["byteMismatches"],
            "probes": {
                name: ("skipped" if p.get("skipped") else (
                    "error" if any(t["result"] == "error"
                                   for t in p.get("targets", {}).values())
                    else "ok"))
                for name, p in cs["probes"].items()
            },
        }
    out["Health"] = health
    # HA control plane (ISSUE 17): raft state + fencing epoch — the
    # operator's answer to "who is the leader, how stable is it, and is
    # the control plane warmed up after the last failover"
    raft = getattr(master, "raft", None)
    if raft is not None:
        with raft.lock:
            out["Raft"] = {
                "term": raft.term,
                "role": raft.role,
                "leaderId": raft.leader_id,
                "commitIndex": raft.commit_index,
                "lastApplied": raft.last_applied,
                "logEntries": len(raft.log),
                "peers": list(raft.peers),
            }
        out["Raft"]["leaderEpoch"] = master.leader_epoch()
        out["Raft"]["warmedUp"] = master.control_warmed()
    return out
