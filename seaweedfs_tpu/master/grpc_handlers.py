"""Master gRPC service: heartbeat ingest, assign/lookup, location pub/sub.

Reference: weed/server/master_grpc_server*.go.
"""

from __future__ import annotations

import queue
import random
import threading
import time

import grpc

from ..pb import master_pb2
from ..storage.file_id import FileId
from ..topology.topology import DataNode


class MasterGrpcService:
    def __init__(self, master):
        self.master = master  # MasterServer
        self.topo = master.topo

    def _require_leader(self, context) -> None:
        """Followers refuse stateful rpcs; the error names the leader so
        clients re-aim (master_grpc_server.go leader checks)."""
        if not self.master.is_leader():
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                f"not the leader; leader is {self.master.leader_grpc()}",
            )

    # -- heartbeat ingest (bidi) -----------------------------------------

    def SendHeartbeat(self, request_iterator, context):
        if not self.master.is_leader():
            # answer once with the leader hint, then end the stream — the
            # volume server reconnects there (volume_grpc_client_to_master)
            yield master_pb2.HeartbeatResponse(
                leader=self.master.leader(),
                leader_grpc=self.master.leader_grpc(),
            )
            return
        node: DataNode | None = None
        try:
            for hb in request_iterator:
                if not self.master.is_leader():
                    # deposed mid-stream: hand the volume server the new
                    # leader hint immediately instead of letting it ride
                    # a dead stream until its next full-pulse timeout
                    yield master_pb2.HeartbeatResponse(
                        leader=self.master.leader(),
                        leader_grpc=self.master.leader_grpc(),
                    )
                    return
                if node is None:
                    node = DataNode(
                        id=f"{hb.ip}:{hb.port}",
                        public_url=hb.public_url or f"{hb.ip}:{hb.port}",
                        grpc_address=f"{hb.ip}:{hb.port + 10000}",
                        data_center=hb.data_center or "DefaultDataCenter",
                        rack=hb.rack or "DefaultRack",
                        max_volumes=sum(hb.max_volume_counts.values()) or 7,
                        max_volume_counts=dict(hb.max_volume_counts),
                    )
                # EVERY beat re-registers (idempotent): if the liveness
                # sweep unregistered a starved node while its stream stayed
                # up, the node must rejoin on its next beat — otherwise it
                # ghosts forever, still heartbeating into a topology that
                # no longer contains it
                node, was_new = self.topo.register_node(node)
                if was_new:
                    # a JOIN changes the EC holder map exactly like a
                    # death: bump the cache-invalidation seq the ack
                    # carries, or every peer's found-tier location cache
                    # (found_ttl 300s) keeps serving the node-less map —
                    # observed live as degraded reads failing "only 9
                    # shards available" for minutes after a dead shard
                    # holder REJOINED (the canary plane found this)
                    self.master.note_topology_change(node.id)
                if hb.max_file_key:
                    self.master.sequencer.set_max(hb.max_file_key)
                new_vids, deleted_vids = [], []
                if hb.volumes or hb.has_no_volumes:
                    before = set(node.volumes)
                    self.topo.sync_volumes(node, list(hb.volumes))
                    after = set(node.volumes)
                    new_vids = sorted(after - before)
                    deleted_vids = sorted(before - after)
                    self.master.rebuild_layouts(node)
                if hb.ec_shards or hb.has_no_ec_shards:
                    self.topo.sync_ec_shards(node, list(hb.ec_shards))
                if (hb.new_volumes or hb.deleted_volumes or hb.new_ec_shards
                        or hb.deleted_ec_shards):
                    self.topo.apply_incremental(node, hb)
                    self.master.rebuild_layouts(node)
                    new_vids += [m.id for m in hb.new_volumes]
                    deleted_vids += [m.id for m in hb.deleted_volumes]
                node.last_seen = time.monotonic()
                if hb.disk_health:
                    # disk-fault plane: record per-dir health, then
                    # react — low_space triggers emergency vacuum via
                    # the lifecycle plane, failing triggers proactive
                    # evacuation via the mass-repair orchestrator
                    node.disk_health = {
                        d.dir: {"state": d.state,
                                "free_bytes": d.free_bytes,
                                "total_bytes": d.total_bytes}
                        for d in hb.disk_health}
                    self.master.note_disk_health(node)
                if hb.HasField("stats"):
                    # federation fallback: keep the node's last stats
                    # snapshot for /cluster/metrics when a live scrape
                    # can't reach it
                    self.master.record_stats_snapshot(
                        node.id, "volume", hb.stats)
                if hb.scrub_findings:
                    # confirmed corruption findings from the node's scrub
                    # daemon: queue them for the maintenance repair pass
                    self.master.record_scrub_findings(
                        node.id, hb.scrub_findings)
                if deleted_vids:
                    # vids gone from this node must leave the writable
                    # sets too — rebuild_layouts only ever registers, so
                    # without this a deleted volume stays assignable on
                    # this node until master restart
                    self.master.unregister_from_layouts(deleted_vids,
                                                        node.id)
                if new_vids or deleted_vids:
                    self.master.broadcast_location(
                        node, new_vids, deleted_vids
                    )
                # the shared background-I/O budget: volume servers point
                # their scrub bucket at this rate so scrub + lifecycle
                # tier traffic can never saturate a node together (0 =
                # keep the node's local default).  During a deadline-
                # bounded mass repair the pushed rate is raised to the
                # floor the bound requires — never below the operator's
                # budget, and only while a budget exists to raise.
                rate = self.master.lifecycle.rate_mbps
                if rate > 0:
                    rate = max(rate, self.master.mass_repair
                               .rate_floor_mbps())
                # warm-up barrier input: one processed beat on a fresh
                # leader means a volume server found us and re-registered
                self.master._beat_count += 1
                yield master_pb2.HeartbeatResponse(
                    volume_size_limit=self.topo.volume_size_limit,
                    leader=self.master.leader(),
                    leader_grpc=self.master.leader_grpc(),
                    lifecycle_rate_mbps=rate,
                    # dead-node notice: a newer seq makes the volume
                    # server drop its EC holder-location caches eagerly
                    dead_node_seq=self.master.dead_node_seq,
                    dead_nodes=self.master.recent_dead_nodes,
                    # fencing epoch: the committed raft term this ack was
                    # produced under — volume servers reject mutating
                    # rpcs stamped with anything older
                    leader_epoch=self.master.leader_epoch(),
                )
        finally:
            if node is not None and context.code() is None:
                pass  # connection drop handled by liveness sweep

    # -- location pub/sub -------------------------------------------------

    def KeepConnected(self, request_iterator, context):
        if not self.master.is_leader():
            # one leader-hint message, then end: clients re-subscribe there
            yield master_pb2.VolumeLocation(leader=self.master.leader())
            return
        q: queue.Queue = queue.Queue()
        self.master.subscribe(q)
        registered_name, registration = "", None
        try:
            req_iter = iter(request_iterator)
            first = next(req_iter, None)
            if first is not None and first.client_type:
                # federation registration: a filer (or other scrapeable
                # client) announces its HTTP address; later requests on
                # the same stream refresh its stats snapshot
                registered_name = first.name
                registration = self.master.register_client(
                    first.name, first.client_type, first.http_address)
                self._ingest_client_stats(first)
                threading.Thread(
                    target=self._drain_client_stream,
                    args=(req_iter,), daemon=True,
                    name="keepconnected-stats").start()
            # initial snapshot: all known volume locations
            with self.topo.lock:
                for n in self.topo.nodes.values():
                    yield master_pb2.VolumeLocation(
                        url=n.id,
                        public_url=n.public_url,
                        new_vids=sorted(set(n.volumes) | set(n.ec_shards)),
                        leader=self.master.leader(),
                        data_center=n.data_center,
                    )
            while context.is_active():
                if not self.master.is_leader():
                    # deposed mid-stream: hand subscribers the new leader
                    # and end, or they'd sit on a silent queue forever
                    yield master_pb2.VolumeLocation(
                        leader=self.master.leader()
                    )
                    return
                try:
                    loc = q.get(timeout=1.0)
                except queue.Empty:
                    continue
                yield loc
        finally:
            self.master.unsubscribe(q)
            if registered_name:
                # token-guarded: only removes OUR registration, never a
                # reconnected stream's fresher one
                self.master.unregister_client(registered_name, registration)

    def _ingest_client_stats(self, req) -> None:
        if req.HasField("stats") and req.http_address:
            self.master.record_stats_snapshot(
                req.http_address, req.client_type or "client", req.stats)

    def _drain_client_stream(self, req_iter) -> None:
        """Consume a registered client's stats refreshes (the stream
        otherwise only matters at open time)."""
        try:
            for req in req_iter:
                if req.client_type:
                    self.master.touch_client(req.name)
                    self._ingest_client_stats(req)
        except Exception:  # noqa: BLE001 — stream teardown races are fine
            pass

    # -- assign / lookup --------------------------------------------------

    def Assign(self, request, context):
        self._require_leader(context)
        try:
            fid, url, public_url, count = self.master.assign(
                count=max(int(request.count), 1),
                collection=request.collection,
                replication=request.replication,
                ttl=request.ttl,
                data_center=request.data_center,
                rack=request.rack,
            )
        except Exception as e:
            return master_pb2.AssignResponse(error=str(e))
        return master_pb2.AssignResponse(
            fid=fid, url=url, public_url=public_url, count=count,
            auth=self.master.sign_fid(fid),
        )

    def LookupVolume(self, request, context):
        self._require_leader(context)
        resp = master_pb2.LookupVolumeResponse()
        for vof in request.volume_or_file_ids:
            entry = resp.volume_id_locations.add(volume_or_file_id=vof)
            try:
                vid = int(vof.split(",", 1)[0])
            except ValueError:
                entry.error = "invalid volume id"
                continue
            locations = self.master.lookup_volume_locations(vid)
            if not locations:
                entry.error = f"volume {vid} not found"
                continue
            for url, public_url in locations:
                entry.locations.add(url=url, public_url=public_url)
        return resp

    def LookupEcVolume(self, request, context):
        self._require_leader(context)
        shard_map = self.topo.lookup_ec_shards(request.volume_id)
        if not shard_map:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"ec volume {request.volume_id} not found",
            )
        resp = master_pb2.LookupEcVolumeResponse(volume_id=request.volume_id)
        for sid in sorted(shard_map):
            e = resp.shard_id_locations.add(shard_id=sid)
            for n in shard_map[sid]:
                # rack/dc ride along so rebuilders can prefer same-rack
                # sources and aggregate one cross-rack partial per rack
                e.locations.add(url=n.id, public_url=n.public_url,
                                data_center=n.data_center, rack=n.rack)
        return resp

    # -- cluster info -----------------------------------------------------

    def VolumeList(self, request, context):
        return master_pb2.VolumeListResponse(
            topology_info=self.topo.to_topology_info(),
            volume_size_limit_mb=self.topo.volume_size_limit // (1 << 20),
        )

    def Statistics(self, request, context):
        total = used = files = 0
        with self.topo.lock:
            for n in self.topo.nodes.values():
                for v in n.volumes.values():
                    if request.collection and v.collection != request.collection:
                        continue
                    used += v.size
                    files += v.file_count
                total += n.max_volumes * self.topo.volume_size_limit
        return master_pb2.StatisticsResponse(
            total_size=total, used_size=used, file_count=files
        )

    def CollectionList(self, request, context):
        resp = master_pb2.CollectionListResponse()
        for name in sorted(self.topo.collections()):
            if name:
                resp.collections.add(name=name)
        return resp

    def CollectionDelete(self, request, context):
        self._require_leader(context)
        self.master.delete_collection(request.name)
        return master_pb2.CollectionDeleteResponse()

    def GetMasterConfiguration(self, request, context):
        return master_pb2.GetMasterConfigurationResponse(
            volume_size_limit_mb=self.topo.volume_size_limit // (1 << 20),
            default_replication=self.master.default_replication,
            leader=self.master.leader(),
        )

    def ListMasterClients(self, request, context):
        return master_pb2.ListMasterClientsResponse()

    def VacuumVolume(self, request, context):
        self._require_leader(context)
        self.master.vacuum(request.garbage_threshold or 0.3)
        return master_pb2.VacuumVolumeResponse()

    # -- lifecycle plane --------------------------------------------------

    def Lifecycle(self, request, context):
        """The volume.lifecycle shell surface: status / policy / run.

        `run` evaluates the policies now; with apply=False it only
        reports the plan (dry run), with apply=True the planned jobs are
        journaled and executed before the response returns."""
        import json

        lc = self.master.lifecycle
        action = request.action or "status"
        if action == "status":
            return master_pb2.LifecycleResponse(
                report=json.dumps(lc.status()))
        if action == "policy":
            try:
                policies = lc.set_policies(request.policy_json)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            return master_pb2.LifecycleResponse(report=policies.dumps())
        if action == "run":
            self._require_leader(context)
            plans = lc.evaluate()
            if request.volume_id:
                plans = [p for p in plans
                         if p["volume_id"] == request.volume_id]
            if request.transition:
                plans = [p for p in plans
                         if p["transition"] == request.transition]
            report = {"planned": plans, "results": []}
            if request.apply:
                accepted = lc.submit(plans)
                # scoped: execute only the jobs THIS request planned —
                # unrelated resumed/queued jobs stay for the controller
                report["results"] = lc.run_pending(
                    wait=True, keys={j["key"] for j in accepted})
            return master_pb2.LifecycleResponse(
                report=json.dumps(report))
        if action == "mass_repair_status":
            return master_pb2.LifecycleResponse(
                report=json.dumps(self.master.mass_repair.status()))
        if action in ("mass_repair_plan", "mass_repair_run"):
            self._require_leader(context)
            mr = self.master.mass_repair
            plans = mr.plan(dead_node=request.node)
            report = {"planned": plans, "results": []}
            if action == "mass_repair_run":
                accepted = mr.submit(plans)
                report["accepted"] = [j["key"] for j in accepted]
                report["results"] = mr.run_wave(mr.pending())
            return master_pb2.LifecycleResponse(
                report=json.dumps(report))
        context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                      f"unknown lifecycle action {action!r} "
                      "(want status|policy|run|mass_repair_status|"
                      "mass_repair_plan|mass_repair_run)")

    # -- admin lock -------------------------------------------------------

    def LeaseAdminToken(self, request, context):
        self._require_leader(context)
        token = self.master.lease_admin_token(
            request.lock_name, request.previous_token
        )
        if token is None:
            context.abort(grpc.StatusCode.ABORTED, "already locked")
        return master_pb2.LeaseAdminTokenResponse(
            token=token, lock_ts_ns=time.time_ns()
        )

    def ReleaseAdminToken(self, request, context):
        self.master.release_admin_token(request.lock_name, request.previous_token)
        return master_pb2.ReleaseAdminTokenResponse()
