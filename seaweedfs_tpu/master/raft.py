"""Raft consensus for the master quorum.

Reference: weed/server/raft_server.go:21-46 (chrislusf/raft over the master
HTTP port, state machine = MaxVolumeId only) and topology/cluster_commands.go
(the MaxVolumeIdCommand).  Re-implemented from the Raft paper rather than
ported: leader election with randomized timeouts, log replication with the
commit-only-current-term rule, and the election restriction on log
up-to-dateness.  The applied state is a small key->int map (op "max_vid"),
so the log stays tiny (one entry per volume growth) and no snapshot/
InstallSnapshot machinery is needed at master scale.

Transport is pluggable: tests inject an in-memory send function; the
MasterServer wires an HTTP JSON POST to each peer's /cluster/raft endpoint
(the reference also multiplexes raft onto the master HTTP listener).
"""

from __future__ import annotations

import concurrent.futures
import json
import os
import random
import threading
import time
from dataclasses import dataclass

from ..util import faultpoint, glog

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

_ROLE_CODE = {FOLLOWER: 0, CANDIDATE: 1, LEADER: 2}

# partition chaos: fires before every outbound raft rpc with
# ctx "<src>-><dst>:<type>", so a `match` substring arms symmetric
# ("8001"), one-way ("a->b") or rpc-type-scoped (":append") drops and
# delays — the asymmetric-partition shapes the paper's safety argument
# must survive
FP_SEND = faultpoint.register("raft.send")


@dataclass
class LogEntry:
    term: int
    command: dict

    def to_json(self) -> dict:
        return {"term": self.term, "command": self.command}

    @staticmethod
    def from_json(d: dict) -> "LogEntry":
        return LogEntry(term=d["term"], command=d["command"])


@dataclass
class Progress:
    next_index: int = 1
    match_index: int = 0


class RaftNode:
    """One consensus participant.  Thread-safe; all RPC handlers are pure
    state transitions under the node lock; timers run in daemon threads.

    ``send(peer_id, message: dict) -> dict | None`` is the transport;
    ``apply_fn(command: dict)`` is called exactly once per committed entry,
    in log order, on every node.
    """

    def __init__(
        self,
        node_id: str,
        peers: list[str],
        send,
        apply_fn=None,
        state_path: str = "",
        election_timeout: tuple[float, float] = (0.4, 0.8),
        heartbeat_interval: float = 0.12,
    ):
        self.id = node_id
        self.peers = [p for p in peers if p != node_id]
        self.send = send
        self.apply_fn = apply_fn or (lambda cmd: None)
        self.state_path = state_path
        # fired (role, term) from a daemon thread on leadership gain/loss
        # only — the owner fences its control plane here (cancel waves on
        # depose, warm up before planning on elect)
        self.on_role_change = None

        self.lock = threading.RLock()
        self.term = 0
        self.voted_for: str | None = None
        self.log: list[LogEntry] = []  # log[i] has index i+1
        self.commit_index = 0
        self.last_applied = 0
        self.role = FOLLOWER
        self.leader_id: str | None = None
        self.progress: dict[str, Progress] = {}
        self.apply_results: dict[int, object] = {}  # log index -> apply value

        self._election_timeout = election_timeout
        self._heartbeat_interval = heartbeat_interval
        self._last_heard = time.monotonic()
        # check-quorum lease: a leader that cannot reach a majority for a
        # full election timeout steps down instead of split-brain-serving
        self._last_quorum_ack = time.monotonic()
        self._stop = threading.Event()
        self._commit_cv = threading.Condition(self.lock)
        # parallel peer RPC pool: one slow/dead peer must never serialize an
        # election or heartbeat round (it livelocks two live candidates)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(2 * len(self.peers), 1),
            thread_name_prefix=f"raft-rpc-{node_id}",
        )
        self._load_state()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        threading.Thread(target=self._election_loop, daemon=True,
                         name=f"raft-elect-{self.id}").start()
        threading.Thread(target=self._leader_loop, daemon=True,
                         name=f"raft-lead-{self.id}").start()

    def stop(self) -> None:
        self._stop.set()
        with self.lock:
            self._commit_cv.notify_all()
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- persistence ---------------------------------------------------------

    def _load_state(self) -> None:
        if not self.state_path or not os.path.exists(self.state_path):
            return
        try:
            with open(self.state_path) as f:
                d = json.load(f)
            self.term = d.get("term", 0)
            self.voted_for = d.get("voted_for")
            self.log = [LogEntry.from_json(e) for e in d.get("log", [])]
        except (OSError, ValueError, KeyError):
            pass

    def _persist(self) -> None:
        if not self.state_path:
            return
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "term": self.term,
                    "voted_for": self.voted_for,
                    "log": [e.to_json() for e in self.log],
                },
                f,
            )
            # raft's stable-storage requirement: term/vote must survive a
            # crash BEFORE any RPC response leaks them, or a node can vote
            # twice in one term after power loss
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.state_path)
        dir_fd = os.open(os.path.dirname(self.state_path) or ".", os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)

    # -- log helpers ---------------------------------------------------------

    def _last_index(self) -> int:
        return len(self.log)

    def _term_at(self, index: int) -> int:
        if index == 0:
            return 0
        return self.log[index - 1].term

    # -- RPC handlers (called by the transport layer) ------------------------

    def handle(self, msg: dict) -> dict:
        kind = msg.get("type")
        if kind == "vote":
            return self.handle_request_vote(msg)
        if kind == "append":
            return self.handle_append_entries(msg)
        return {"error": f"unknown raft message {kind!r}"}

    def handle_request_vote(self, msg: dict) -> dict:
        with self.lock:
            term = msg["term"]
            if term > self.term:
                self._become_follower(term)
            granted = False
            if term == self.term and self.voted_for in (None, msg["candidate"]):
                # election restriction: candidate log must be >= ours
                up_to_date = (
                    msg["last_log_term"] > self._term_at(self._last_index())
                    or (
                        msg["last_log_term"] == self._term_at(self._last_index())
                        and msg["last_log_index"] >= self._last_index()
                    )
                )
                if up_to_date:
                    granted = True
                    self.voted_for = msg["candidate"]
                    self._last_heard = time.monotonic()
                    self._persist()
            return {"term": self.term, "granted": granted}

    def handle_append_entries(self, msg: dict) -> dict:
        with self.lock:
            term = msg["term"]
            if term < self.term:
                return {"term": self.term, "success": False}
            if term > self.term or self.role != FOLLOWER:
                self._become_follower(term)
            self.leader_id = msg["leader"]
            self._last_heard = time.monotonic()
            prev_index = msg["prev_log_index"]
            if prev_index > self._last_index() or (
                prev_index > 0
                and self._term_at(prev_index) != msg["prev_log_term"]
            ):
                return {"term": self.term, "success": False,
                        "hint": min(prev_index, self._last_index() + 1)}
            entries = [LogEntry.from_json(e) for e in msg.get("entries", [])]
            idx = prev_index
            changed = False
            for e in entries:
                idx += 1
                if idx <= self._last_index():
                    if self._term_at(idx) != e.term:
                        del self.log[idx - 1 :]  # conflict: truncate
                        self.log.append(e)
                        changed = True
                else:
                    self.log.append(e)
                    changed = True
            if changed:
                self._persist()
            if msg["leader_commit"] > self.commit_index:
                self.commit_index = min(msg["leader_commit"], self._last_index())
                self._apply_committed()
            self._note_metrics()
            return {"term": self.term, "success": True,
                    "match": prev_index + len(entries)}

    # -- state transitions ---------------------------------------------------

    def _become_follower(self, term: int) -> None:
        was_leader = self.role == LEADER
        if term > self.term:
            # votedFor is PER TERM (Raft fig. 2): resetting it at the same
            # term would let this node vote twice in one term after a
            # candidate->follower or check-quorum step-down
            self.voted_for = None
        self.term = term
        self.role = FOLLOWER
        self._persist()
        self._note_metrics()
        if was_leader:
            glog.warning("raft %s: deposed at term %d", self.id, term)
            self._notify_role(FOLLOWER, term)

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader_id = self.id
        self._last_quorum_ack = time.monotonic()
        self.progress = {
            p: Progress(next_index=self._last_index() + 1) for p in self.peers
        }
        # replicate a no-op so entries from prior terms can commit
        # (Raft §5.4.2 commit-only-current-term rule needs a current entry)
        self.log.append(LogEntry(self.term, {"op": "noop"}))
        self._persist()
        self._note_metrics()
        glog.info("raft %s: elected leader at term %d", self.id, self.term)
        self._notify_role(LEADER, self.term)

    def _notify_role(self, role: str, term: int) -> None:
        from ..stats.metrics import RAFT_LEADER_CHANGES

        RAFT_LEADER_CHANGES.labels(self.id).inc()
        cb = self.on_role_change
        if cb is not None:
            # asynchronously: the callback fences executors/journals and
            # must never run under (or wait on) the raft lock
            threading.Thread(
                target=cb, args=(role, term), daemon=True,
                name=f"raft-role-{self.id}",
            ).start()

    def _note_metrics(self) -> None:
        from ..stats import metrics as m

        m.RAFT_TERM.labels(self.id).set(self.term)
        m.RAFT_ROLE.labels(self.id).set(_ROLE_CODE[self.role])
        m.RAFT_COMMIT_INDEX.labels(self.id).set(self.commit_index)
        m.RAFT_LOG_ENTRIES.labels(self.id).set(len(self.log))

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            cmd = self.log[self.last_applied - 1].command
            if cmd.get("op") != "noop":
                try:
                    result = self.apply_fn(cmd)
                    # keep recent results so propose_and_get can read the
                    # value its own entry produced (bounded window)
                    self.apply_results[self.last_applied] = result
                    if len(self.apply_results) > 1024:
                        for k in sorted(self.apply_results)[:-512]:
                            del self.apply_results[k]
                except Exception as e:  # an apply failure risks replica
                    # divergence — it must at least be visible
                    glog.error("raft apply of entry %d failed: %s",
                               self.last_applied, e)
        self._commit_cv.notify_all()

    # -- election ------------------------------------------------------------

    def _election_deadline(self) -> float:
        lo, hi = self._election_timeout
        return random.uniform(lo, hi)

    def _election_loop(self) -> None:
        deadline = self._election_deadline()
        while not self._stop.is_set():
            time.sleep(0.02)
            with self.lock:
                if self.role == LEADER:
                    self._last_heard = time.monotonic()
                    # check quorum: a partitioned leader cannot commit, so
                    # keeping the LEADER role only extends the split-brain
                    # window in which it hands out assigns and repair
                    # batches another leader will conflict with
                    if (time.monotonic() - self._last_quorum_ack
                            > self._election_timeout[1]):
                        glog.warning(
                            "raft %s: lost quorum contact for %.1fs, "
                            "stepping down", self.id,
                            time.monotonic() - self._last_quorum_ack)
                        self._become_follower(self.term)
                    continue
                waited = time.monotonic() - self._last_heard
            if waited >= deadline:
                self._run_election()
                deadline = self._election_deadline()

    def _run_election(self) -> None:
        with self.lock:
            self.role = CANDIDATE
            self.term += 1
            self.voted_for = self.id
            self.leader_id = None
            self._persist()
            self._note_metrics()
            term = self.term
            req = {
                "type": "vote",
                "term": term,
                "candidate": self.id,
                "last_log_index": self._last_index(),
                "last_log_term": self._term_at(self._last_index()),
            }
            self._last_heard = time.monotonic()
        quorum = (len(self.peers) + 1) // 2 + 1
        votes = 1
        futures = list(self._submit_sends({p: req for p in self.peers}))
        try:
            for fut in concurrent.futures.as_completed(futures, timeout=2.0):
                resp = fut.result()
                if resp is None:
                    continue
                with self.lock:
                    if resp.get("term", 0) > self.term:
                        self._become_follower(resp["term"])
                        return
                    if self.term != term or self.role != CANDIDATE:
                        return  # stale election
                if resp.get("granted"):
                    votes += 1
                if votes >= quorum:
                    break  # don't wait for stragglers/dead peers
        except concurrent.futures.TimeoutError:
            pass
        with self.lock:
            if self.role == CANDIDATE and self.term == term and votes >= quorum:
                self._become_leader()

    # -- leader replication ---------------------------------------------------

    def _leader_loop(self) -> None:
        while not self._stop.is_set():
            with self.lock:
                is_leader = self.role == LEADER
            if is_leader:
                self._replicate_once()
                time.sleep(self._heartbeat_interval)
            else:
                time.sleep(0.02)

    def _replicate_once(self) -> None:
        with self.lock:
            if self.role != LEADER:
                return
            term = self.term
            reqs = {}
            for p in self.peers:
                prog = self.progress[p]
                prev = prog.next_index - 1
                entries = [
                    e.to_json() for e in self.log[prog.next_index - 1 :]
                ]
                reqs[p] = {
                    "type": "append",
                    "term": term,
                    "leader": self.id,
                    "prev_log_index": prev,
                    "prev_log_term": self._term_at(prev),
                    "entries": entries,
                    "leader_commit": self.commit_index,
                }
        futures = self._submit_sends(reqs)
        acks = 1  # self
        try:
            for fut in concurrent.futures.as_completed(futures, timeout=2.0):
                p = futures[fut]
                resp = fut.result()
                if resp is None:
                    continue
                acks += 1  # any live response is quorum contact
                with self.lock:
                    if resp.get("term", 0) > self.term:
                        self._become_follower(resp["term"])
                        return
                    if self.role != LEADER or self.term != term:
                        return
                    prog = self.progress[p]
                    if resp.get("success"):
                        prog.match_index = max(
                            prog.match_index, resp.get("match", 0)
                        )
                        prog.next_index = prog.match_index + 1
                    else:
                        prog.next_index = max(1, resp.get(
                            "hint", prog.next_index - 1
                        ))
                self._advance_commit()
        except concurrent.futures.TimeoutError:
            pass
        if acks >= (len(self.peers) + 1) // 2 + 1:
            with self.lock:
                self._last_quorum_ack = time.monotonic()

    def _advance_commit(self) -> None:
        with self.lock:
            if self.role != LEADER:
                return
            for n in range(self._last_index(), self.commit_index, -1):
                if self._term_at(n) != self.term:
                    break  # only commit entries from the current term
                count = 1 + sum(
                    1 for p in self.peers if self.progress[p].match_index >= n
                )
                if count >= (len(self.peers) + 1) // 2 + 1:
                    self.commit_index = n
                    self._apply_committed()
                    self._note_metrics()
                    break

    def _send_to(self, peer: str, msg: dict) -> dict | None:
        from ..stats.metrics import RAFT_RPC

        kind = msg.get("type", "?")
        try:
            # drop / delay / one-way partitions arm here by ctx substring
            faultpoint.inject(FP_SEND, ctx=f"{self.id}->{peer}:{kind}")
        except Exception:
            RAFT_RPC.labels(kind, "dropped").inc()
            return None
        try:
            resp = self.send(peer, msg)
        except Exception:
            RAFT_RPC.labels(kind, "error").inc()
            return None
        RAFT_RPC.labels(kind, "ok").inc()
        return resp

    def _submit_sends(self, reqs: dict) -> dict:
        """Submit parallel peer sends; {} once the node is stopping (the
        pool rejects new futures after shutdown)."""
        if self._stop.is_set():
            return {}
        try:
            return {
                self._pool.submit(self._send_to, p, req): p
                for p, req in reqs.items()
            }
        except RuntimeError:  # pool shut down concurrently
            return {}

    # -- client API ----------------------------------------------------------

    def is_leader(self) -> bool:
        with self.lock:
            return self.role == LEADER

    def leader_epoch(self) -> int:
        """Fencing epoch = the term this node leads under; 0 off-throne.
        Terms are monotonic across failovers, so any rpc stamped with an
        older epoch is provably from a deposed leader."""
        with self.lock:
            return self.term if self.role == LEADER else 0

    def propose(self, command: dict, timeout: float = 5.0) -> bool:
        """Leader-only: append, replicate, wait for commit+apply."""
        ok, _ = self.propose_and_get(command, timeout)
        return ok

    def propose_and_get(self, command: dict,
                        timeout: float = 5.0) -> tuple[bool, object]:
        """Like propose, but returns (ok, value-returned-by-apply_fn).

        Commands whose outcome depends on prior state (e.g. "increment the
        max volume id") MUST compute it inside apply_fn — apply runs in log
        order on every replica, so a freshly elected leader that hasn't yet
        applied the old leader's tail cannot hand out a stale value."""
        with self.lock:
            if self.role != LEADER:
                return False, None
            appended_term = self.term
            self.log.append(LogEntry(appended_term, command))
            self._persist()
            index = self._last_index()
        self._replicate_once()
        deadline = time.monotonic() + timeout
        with self.lock:
            while self.commit_index < index:
                if self.role != LEADER or self._stop.is_set():
                    return False, None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False, None
                self._commit_cv.wait(min(remaining, 0.05))
            # the committed entry at our index must still be OURS: after a
            # depose/re-elect cycle another leader's entry may occupy it,
            # and returning its apply value would hand out duplicate state
            if (index > self._last_index()
                    or self._term_at(index) != appended_term):
                return False, None
            return True, self.apply_results.get(index)
