"""File-key sequencers (reference: weed/sequence/ — memory, etcd, snowflake).

The memory sequencer is the default; the snowflake variant gives collision-
free ids across multiple masters without coordination.
"""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = max(start, 1)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        # reference bumps when counter <= seenValue: a heartbeat reporting
        # max_file_key equal to the current counter must still advance it,
        # or the next assign would reuse a live needle id
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node | 12-bit sequence."""

    EPOCH_MS = 1_600_000_000_000

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        if not 1 <= count <= 1 << 12:
            # a range can never exceed the 12-bit sequence space, or ids
            # would carry into the node-id bits and collide across masters
            raise ValueError(f"snowflake range {count} exceeds 4096")
        with self._lock:
            now = int(time.time() * 1000) - self.EPOCH_MS
            if now < self._last_ms:
                now = self._last_ms  # keep monotonic under clock skew
            if now == self._last_ms:
                first = self._seq + 1
                if first + count - 1 >= 1 << 12:
                    # sequence exhausted: advance to the next logical ms.
                    # _last_ms is monotonic (clamp above), so this ms can
                    # never be re-entered at seq 0 even if the wall clock
                    # later catches up — no duplicate ids, no lock-held spin.
                    now += 1
                    first = 0
            else:
                first = 0
            self._seq = first + count - 1
            self._last_ms = now
            return (now << 22) | (self.node_id << 12) | first

    def set_max(self, seen_value: int) -> None:
        pass  # timestamps make collisions impossible


class EtcdSequencer:
    """Chunked ids leased from etcd via CAS (etcd_sequencer.go:26-110).

    Holds a local range [current, max); when exhausted, atomically bumps
    the shared counter key by `steps` with a value-CAS transaction, so
    multiple masters lease disjoint ranges from one etcd cluster.  Built
    on the framework-native etcd v3 client (util.etcd.EtcdClient).
    """

    KEY = b"/seaweedfs/master/sequence"
    DEFAULT_STEPS = 500  # reference DefaultEtcdSteps

    def __init__(self, endpoint: str = "127.0.0.1:2379",
                 steps: int = DEFAULT_STEPS):
        from ..util.etcd import EtcdClient

        self._client = EtcdClient(endpoint)
        self._steps = max(1, steps)
        self._lock = threading.Lock()
        self._current = 0
        self._max = 0  # exclusive

    def _lease_range(self, need: int) -> None:
        steps = self._steps + (need if need > self._steps else 0)
        while True:
            cur = self._client.get(self.KEY)
            base = int(cur) if cur else 1
            if self._client.cas(self.KEY, cur, str(base + steps).encode()):
                self._current, self._max = base, base + steps
                return

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            if self._current + count > self._max:
                self._lease_range(count)
            start = self._current
            self._current += count
            return start

    def set_max(self, seen_value: int) -> None:
        """A volume server reported ids >= the shared counter: push the
        etcd counter past them AND drop the local lease — ids below
        seen_value are live needle ids, so handing out the rest of the
        current range would alias existing needles."""
        with self._lock:
            # compare against the NEXT id to hand out, not the lease end:
            # any id <= seen_value may be a live needle, so a lease whose
            # cursor sits at or below it must be dropped even if the
            # lease's end extends past it
            if seen_value < self._current:
                return
            self._current = self._max = 0  # force a fresh lease
            while True:
                cur = self._client.get(self.KEY)
                base = int(cur) if cur else 1
                if base > seen_value:
                    return
                if self._client.cas(self.KEY, cur,
                                    str(seen_value + 1).encode()):
                    return

    def peek(self) -> int:
        with self._lock:
            return self._current


def make_sequencer(kind: str = "memory", node_id: int = 0,
                   etcd_endpoint: str = "127.0.0.1:2379"):
    if kind == "memory":
        return MemorySequencer()
    if kind == "snowflake":
        return SnowflakeSequencer(node_id)
    if kind == "etcd":
        return EtcdSequencer(etcd_endpoint)
    raise ValueError(f"unknown sequencer {kind!r}")
