"""File-key sequencers (reference: weed/sequence/ — memory, etcd, snowflake).

The memory sequencer is the default; the snowflake variant gives collision-
free ids across multiple masters without coordination.
"""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = max(start, 1)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        with self._lock:
            if seen_value > self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node | 12-bit sequence."""

    EPOCH_MS = 1_600_000_000_000

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            now = int(time.time() * 1000) - self.EPOCH_MS
            if now == self._last_ms:
                self._seq += count
                if self._seq >= 1 << 12:
                    time.sleep(0.001)
                    now += 1
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = now
            return (now << 22) | (self.node_id << 12) | self._seq

    def set_max(self, seen_value: int) -> None:
        pass  # timestamps make collisions impossible


def make_sequencer(kind: str = "memory", node_id: int = 0):
    if kind == "memory":
        return MemorySequencer()
    if kind == "snowflake":
        return SnowflakeSequencer(node_id)
    raise ValueError(f"unknown sequencer {kind!r}")
