"""File-key sequencers (reference: weed/sequence/ — memory, etcd, snowflake).

The memory sequencer is the default; the snowflake variant gives collision-
free ids across multiple masters without coordination.
"""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = max(start, 1)
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        # reference bumps when counter <= seenValue: a heartbeat reporting
        # max_file_key equal to the current counter must still advance it,
        # or the next assign would reuse a live needle id
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit node | 12-bit sequence."""

    EPOCH_MS = 1_600_000_000_000

    def __init__(self, node_id: int = 0):
        self.node_id = node_id & 0x3FF
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        if not 1 <= count <= 1 << 12:
            # a range can never exceed the 12-bit sequence space, or ids
            # would carry into the node-id bits and collide across masters
            raise ValueError(f"snowflake range {count} exceeds 4096")
        with self._lock:
            now = int(time.time() * 1000) - self.EPOCH_MS
            if now < self._last_ms:
                now = self._last_ms  # keep monotonic under clock skew
            if now == self._last_ms:
                first = self._seq + 1
                if first + count - 1 >= 1 << 12:
                    # sequence exhausted: advance to the next logical ms.
                    # _last_ms is monotonic (clamp above), so this ms can
                    # never be re-entered at seq 0 even if the wall clock
                    # later catches up — no duplicate ids, no lock-held spin.
                    now += 1
                    first = 0
            else:
                first = 0
            self._seq = first + count - 1
            self._last_ms = now
            return (now << 22) | (self.node_id << 12) | first

    def set_max(self, seen_value: int) -> None:
        pass  # timestamps make collisions impossible


def make_sequencer(kind: str = "memory", node_id: int = 0):
    if kind == "memory":
        return MemorySequencer()
    if kind == "snowflake":
        return SnowflakeSequencer(node_id)
    if kind == "etcd":
        raise ValueError(
            "the etcd sequencer needs an etcd endpoint + client, which "
            "this deployment does not ship; use memory or snowflake")
    raise ValueError(f"unknown sequencer {kind!r}")
