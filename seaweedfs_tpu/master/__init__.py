from .server import MasterServer  # noqa: F401
