"""Flight recorder: alert-triggered cluster debug bundles.

The SLO engine can page within seconds, but by the time an operator
answers the page the evidence is rotating out of the per-node rings.
The flight recorder closes that gap: the moment an alert transitions to
firing (or on demand via `GET /cluster/debug/capture` / the shell's
`cluster.debug -capture`), the master fans out to every live node and
snapshots what the rings hold RIGHT NOW into one bundle —

  * the full metrics exposition per node,
  * the span rings (plus a targeted fetch of the alert's exemplar
    trace id, so the paged request's timeline is pinned even if the
    recent-ring has already rotated past it),
  * the continuous profiler's window history,
  * the heavy-hitter tables,
  * master-local control-plane state (raft, lifecycle, disk health,
    alert states),
  * and, for an alert capture, the stitched cluster-wide exemplar
    trace.

Bundles persist under `-debugDir` with bounded retention (an in-memory
ring when no directory is configured) and are listed from
`/cluster/alerts` and `/cluster/debug`.  Capture bytes are charged to
the shared background-I/O budget (the lifecycle TokenBucket), so a page
storm cannot amplify the outage it is documenting.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..stats.metrics import DEBUG_BUNDLE_SECONDS, DEBUG_BUNDLES, REGISTRY
from ..telemetry import debug_traces_body
from ..util import glog
from .observability import (
    FEDERATION_TIMEOUT_S,
    _scrape,
    cluster_traces,
    federation_targets,
)

RETAIN_VAR = "SEAWEEDFS_TPU_DEBUG_BUNDLE_RETAIN"
COOLDOWN_VAR = "SEAWEEDFS_TPU_DEBUG_BUNDLE_COOLDOWN_S"
DEFAULT_RETAIN = 8
DEFAULT_COOLDOWN_S = 60.0

# per-node ring endpoints snapshotted into every bundle
_NODE_SECTIONS = (
    ("metrics", "/metrics"),
    ("spans", "/debug/traces?limit=200"),
    ("profile", "/debug/profile/history"),
    ("hot", "/debug/hot"),
)


def _env_num(var: str, default: float) -> float:
    try:
        return float(os.environ.get(var, "") or default)
    except ValueError:
        return default


class FlightRecorder:
    def __init__(self, master, debug_dir: str = "",
                 retain: int | None = None,
                 cooldown_s: float | None = None):
        self.master = master
        self.debug_dir = debug_dir
        self.retain = (int(_env_num(RETAIN_VAR, DEFAULT_RETAIN))
                       if retain is None else int(retain))
        self.retain = max(1, self.retain)
        self.cooldown_s = (_env_num(COOLDOWN_VAR, DEFAULT_COOLDOWN_S)
                           if cooldown_s is None else float(cooldown_s))
        if debug_dir:
            os.makedirs(debug_dir, exist_ok=True)
        # one capture at a time; alert storms coalesce into the capture
        # already in flight (its bundle holds the same evidence)
        self._capture_lock = threading.Lock()
        self._last_capture = 0.0
        self._seq = 0
        self._seq_lock = threading.Lock()
        # in-memory ring when no debug_dir is configured
        self._mem: deque[tuple[str, dict]] = deque(maxlen=self.retain)

    # -- slo sink ---------------------------------------------------------

    def sink(self, alert: dict) -> None:
        """SloEngine sink: a transition to firing captures a bundle in
        the background.  Runs on the engine's evaluation thread, so the
        fan-out must not happen inline."""
        if alert.get("state") != "firing":
            return
        now = time.monotonic()
        if now - self._last_capture < self.cooldown_s:
            return
        threading.Thread(
            target=self._capture_safe, args=("alert", alert),
            daemon=True, name="flight-capture").start()

    def _capture_safe(self, trigger: str, alert: dict | None) -> None:
        try:
            self.capture(trigger=trigger, alert=alert)
        except Exception as e:  # noqa: BLE001 — capture must never raise
            glog.error("flight recorder capture failed: %s", e)

    # -- capture ----------------------------------------------------------

    def capture(self, trigger: str = "manual",
                alert: dict | None = None) -> dict:
        """Snapshot every live node's rings into one bundle.  Returns
        the bundle's summary {name, nodes, sizeBytes, ...}; raises only
        on a capture already in flight (the caller's 409)."""
        if not self._capture_lock.acquire(blocking=False):
            raise RuntimeError("a bundle capture is already in progress")
        t0 = time.perf_counter()
        try:
            self._last_capture = time.monotonic()
            bundle = self._collect(trigger, alert)
            payload = json.dumps(bundle).encode()
            # charge the shared background budget BEFORE persisting: a
            # page during an overload waits its turn behind lifecycle
            # and scrub traffic instead of adding unthrottled I/O
            self.master.lifecycle.bucket.consume(
                len(payload), stop=self.master._stop)
            name = bundle["name"]
            if self.debug_dir:
                path = os.path.join(self.debug_dir, name + ".json")
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(payload)
                os.replace(tmp, path)
                self._prune()
            else:
                self._mem.append((name, bundle))
            DEBUG_BUNDLES.labels(trigger, "ok").inc()
            glog.info("flight recorder: captured %s (%d nodes, %d bytes,"
                      " trigger=%s)", name, len(bundle["nodes"]),
                      len(payload), trigger)
            return {
                "name": name,
                "trigger": trigger,
                "at": bundle["at"],
                "nodes": sorted(bundle["nodes"]),
                "sizeBytes": len(payload),
                "alert": (alert or {}).get("slo", ""),
            }
        except Exception:
            DEBUG_BUNDLES.labels(trigger, "error").inc()
            raise
        finally:
            DEBUG_BUNDLE_SECONDS.observe(time.perf_counter() - t0)
            self._capture_lock.release()

    def _collect(self, trigger: str, alert: dict | None) -> dict:
        master = self.master
        with self._seq_lock:
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
        name = f"bundle-{stamp}-{trigger}-{seq}"
        exemplar_ids = [e["traceId"] for e in (alert or {}).get(
            "exemplars", ()) if e.get("traceId")]

        def fetch_node(t: dict) -> tuple[str, dict]:
            base = f"http://{t['http_address']}"
            sections: dict = {"type": t["type"]}
            for key, path in _NODE_SECTIONS:
                try:
                    text = _scrape(base + path, FEDERATION_TIMEOUT_S)
                    sections[key] = (text if key == "metrics"
                                     else json.loads(text))
                except Exception as e:  # noqa: BLE001 — partial is fine
                    sections.setdefault("errors", {})[key] = str(e)
            # pin the exemplar trace: the targeted query hits the
            # important-span ring even after the recent ring rotated
            for tid in exemplar_ids:
                try:
                    doc = json.loads(_scrape(
                        f"{base}/debug/traces?trace={tid}&limit=200",
                        FEDERATION_TIMEOUT_S))
                except Exception:  # noqa: BLE001
                    continue
                spans = sections.setdefault("spans", {"traces": []})
                have = {tr.get("traceId")
                        for tr in spans.get("traces", ())}
                for tr in doc.get("traces", ()):
                    if tr.get("traceId") not in have:
                        spans.setdefault("traces", []).append(tr)
            return t["instance"], sections

        targets = federation_targets(master)
        futures = [master.federation_pool.submit(fetch_node, t)
                   for t in targets]

        # the master's own rings, read in-process (no self-scrape)
        from ..telemetry import hotkeys as _hotkeys
        from ..util import profiler as _profiler

        self_sections: dict = {
            "type": "master",
            "metrics": REGISTRY.render(),
            "spans": json.loads(debug_traces_body(200)),
            "profile": _profiler.continuous_history(),
            "hot": _hotkeys.snapshot(),
        }
        nodes = {f"{master.ip}:{master.port}": self_sections}
        for fut in futures:
            instance, sections = fut.result()
            nodes.setdefault(instance, sections)

        bundle = {
            "name": name,
            "at": time.time(),
            "trigger": trigger,
            "cluster": {
                "leader": master.leader(),
                "isLeader": master.is_leader(),
                "lifecycle": master.lifecycle.status(),
                "sloStates": master.slo.status(evaluate_if_idle=False),
            },
            "nodes": nodes,
        }
        if alert is not None:
            bundle["alert"] = alert
            if exemplar_ids:
                # the cluster-wide stitched timeline of the paged
                # request — the "what exactly was slow, where" answer
                bundle["exemplarTrace"] = cluster_traces(
                    master, exemplar_ids[0], 200)
        raft = getattr(master, "raft", None)
        if raft is not None:
            with raft.lock:
                bundle["cluster"]["raft"] = {
                    "term": raft.term, "role": raft.role,
                    "leaderId": raft.leader_id,
                    "commitIndex": raft.commit_index,
                }
        return bundle

    # -- retention / listing ----------------------------------------------

    def _paths(self) -> list[str]:
        if not self.debug_dir:
            return []
        try:
            names = os.listdir(self.debug_dir)
        except OSError:
            return []
        return sorted(
            os.path.join(self.debug_dir, n) for n in names
            if n.startswith("bundle-") and n.endswith(".json"))

    def _prune(self) -> None:
        paths = self._paths()
        for path in paths[:-self.retain]:
            try:
                os.unlink(path)
            except OSError:
                pass

    def list_bundles(self) -> list[dict]:
        """Newest first: [{name, sizeBytes, ageS}]."""
        out = []
        now = time.time()
        if self.debug_dir:
            for path in self._paths():
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                out.append({
                    "name": os.path.basename(path)[:-len(".json")],
                    "sizeBytes": st.st_size,
                    "ageS": round(max(0.0, now - st.st_mtime), 1),
                })
        else:
            for name, doc in self._mem:
                out.append({
                    "name": name,
                    "sizeBytes": len(json.dumps(doc)),
                    "ageS": round(max(0.0, now - doc["at"]), 1),
                })
        out.sort(key=lambda b: b["ageS"])
        return out

    def bundle(self, name: str) -> dict | None:
        if not name.startswith("bundle-") or "/" in name or ".." in name:
            return None
        if self.debug_dir:
            path = os.path.join(self.debug_dir, name + ".json")
            try:
                with open(path, "rb") as f:
                    return json.loads(f.read())
            except (OSError, ValueError):
                return None
        for mem_name, doc in self._mem:
            if mem_name == name:
                return doc
        return None
