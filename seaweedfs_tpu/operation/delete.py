"""Batched blob deletion across volume servers.

Reference: weed/operation/delete_content.go — group file ids by volume,
resolve locations, fan out BatchDelete rpcs per server.
"""

from __future__ import annotations

import grpc

from ..pb import rpc as rpclib
from ..pb import volume_server_pb2 as vs
from ..util import failsafe


def delete_file_id(lookup, fid: str, jwt: str = "") -> bool:
    """Delete one file id; lookup(vid) -> [Location]."""
    results = delete_file_ids(lookup, [fid])
    return results.get(fid, False)


def delete_file_ids(lookup, fids: list[str]) -> dict[str, bool]:
    """Delete many file ids; returns fid -> deleted?

    ``lookup`` is a callable vid -> [Location]; one BatchDelete rpc goes to
    the first holder of each volume (the server fans out to replicas).
    """
    by_server: dict[str, list[str]] = {}
    results: dict[str, bool] = {}
    for fid in fids:
        try:
            vid = int(fid.split(",", 1)[0])
        except ValueError:
            results[fid] = False
            continue
        locs = lookup(vid)
        if not locs:
            results[fid] = False
            continue
        grpc_addr = _grpc_address(locs[0].url)
        by_server.setdefault(grpc_addr, []).append(fid)
    for server, server_fids in by_server.items():
        # deletes are idempotent (a re-deleted needle answers not-found),
        # so transient rpc failures retry under the shared policy
        try:
            resp = failsafe.call(
                lambda s=server, f=server_fids: rpclib.volume_server_stub(
                    s, timeout=30).BatchDelete(
                        vs.BatchDeleteRequest(file_ids=f)),
                op="batch_delete", retry_type="operation",
                policy=failsafe.RPC_POLICY, peer=server, idempotent=True,
            )
            for r in resp.results:
                results[r.file_id] = not r.error
        except (grpc.RpcError, failsafe.CircuitOpenError, OSError):
            for fid in server_fids:
                results[fid] = False
    return results


def _grpc_address(http_url: str) -> str:
    host, port = http_url.rsplit(":", 1)
    return f"{host}:{int(port) + 10000}"
