"""Upload / download blob content to/from volume servers over HTTP.

Reference: weed/operation/upload_content.go:69-191 — multipart POST with
optional gzip compression, retried; the server answers {name,size,eTag}.

Both directions run under the shared failsafe policy (util/failsafe.py):
uploads retry only idempotency-safe failures (connect errors and 5xx —
the body was provably not acknowledged), downloads retry any transient
failure, and both are breaker-gated per volume server.
"""

from __future__ import annotations

import gzip
import json
import urllib.error
import uuid
from dataclasses import dataclass

from ..telemetry import trace
from ..util import connpool, failsafe, faultpoint
from ..util.http_util import netloc as _peer_of
from ..util.http_util import trace_headers

_COMPRESSIBLE_PREFIXES = ("text/", "application/json", "application/xml")

FP_UPLOAD = faultpoint.register("operation.upload")
FP_DOWNLOAD = faultpoint.register("operation.download")


@dataclass
class UploadResult:
    name: str
    size: int
    etag: str
    mime: str = ""
    gzipped: bool = False


class VolumeFullError(RuntimeError):
    """Typed volume-full rejection (HTTP 409 from the volume server's
    disk-fault plane): the target cannot take this write and retrying
    it is pointless — the caller should RE-ASSIGN immediately (the
    master stops handing out the full volume within one heartbeat)."""


def _is_volume_full(exc: BaseException) -> bool:
    seen = 0
    while exc is not None and seen < 8:
        if isinstance(exc, urllib.error.HTTPError) and exc.code == 409:
            return True
        exc = exc.__cause__ or exc.__context__
        seen += 1
    return False


def upload_data(
    url: str,
    data: bytes,
    filename: str = "",
    mime: str = "",
    compress: bool = False,
    jwt: str = "",
    retries: int = 3,
    timeout: float = 30.0,
) -> UploadResult:
    """POST data as multipart/form-data to a volume-server fid url."""
    gzipped = False
    payload = data
    if compress and _is_compressible(mime, filename) and len(data) > 128:
        squeezed = gzip.compress(data, compresslevel=3)
        if len(squeezed) < len(data) * 0.9:
            payload = squeezed
            gzipped = True

    boundary = uuid.uuid4().hex
    head = (
        f"--{boundary}\r\n"
        f'Content-Disposition: form-data; name="file"; '
        f'filename="{filename or "file"}"\r\n'
        f"Content-Type: {mime or 'application/octet-stream'}\r\n"
        + ("Content-Encoding: gzip\r\n" if gzipped else "")
        + "\r\n"
    ).encode()
    body = head + payload + f"\r\n--{boundary}--\r\n".encode()
    headers = {"Content-Type": f"multipart/form-data; boundary={boundary}"}
    if jwt:
        headers["Authorization"] = f"BEARER {jwt}"

    def attempt() -> UploadResult:
        faultpoint.inject(FP_UPLOAD, ctx=url)
        with trace.child_span("http.upload", url=url, bytes=len(payload)):
            # traceparent captured inside the span: the volume
            # server's span must parent to http.upload, not above it
            with connpool.request(
                    "POST", url, body=body, headers=trace_headers(headers),
                    timeout=failsafe.attempt_timeout(timeout)) as resp:
                out = json.loads(resp.read() or b"{}")
        return UploadResult(
            name=out.get("name", filename),
            size=out.get("size", len(data)),
            etag=out.get("eTag", ""),
            mime=mime,
            gzipped=gzipped,
        )

    policy = failsafe.RetryPolicy(
        max_attempts=max(1, retries),
        base_delay=failsafe.UPLOAD_POLICY.base_delay,
        max_delay=failsafe.UPLOAD_POLICY.max_delay,
    )
    try:
        return failsafe.call(
            attempt, op="upload", retry_type="operation",
            policy=policy, peer=_peer_of(url), idempotent=False,
        )
    except Exception as e:
        if _is_volume_full(e):
            raise VolumeFullError(
                f"volume full at {url} (re-assign): {e}") from e
        raise RuntimeError(f"upload to {url} failed: {e}") from e


def download(url: str, timeout: float = 30.0,
             range_header: str | None = None, retries: int = 3,
             use_breaker: bool = True) -> bytes:
    """GET a blob; idempotent, so any transient failure retries.

    `use_breaker=False` skips the per-peer breaker gate — for callers
    that already gate the peer themselves (failover loops), where a
    second allow() on the same breaker would starve its own half-open
    probe."""

    def attempt() -> bytes:
        with trace.child_span("http.download", url=url):
            headers = trace_headers(
                {"Range": range_header} if range_header else {})
            with connpool.request(
                    "GET", url, headers=headers,
                    timeout=failsafe.attempt_timeout(timeout)) as resp:
                blob = resp.read()
        return faultpoint.inject(FP_DOWNLOAD, ctx=url, data=blob)

    policy = failsafe.RetryPolicy(
        max_attempts=max(1, retries),
        base_delay=failsafe.DOWNLOAD_POLICY.base_delay,
        max_delay=failsafe.DOWNLOAD_POLICY.max_delay,
    )
    return failsafe.call(
        attempt, op="download", retry_type="operation",
        policy=policy, peer=_peer_of(url) if use_breaker else None,
        idempotent=True,
    )


def _is_compressible(mime: str, filename: str) -> bool:
    if any(mime.startswith(p) for p in _COMPRESSIBLE_PREFIXES):
        return True
    return filename.endswith((".txt", ".csv", ".json", ".log", ".xml", ".html"))
