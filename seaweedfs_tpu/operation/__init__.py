"""One-shot cluster operations: assign, upload, delete.

Reference surface: weed/operation (assign_file_id.go, upload_content.go:69,
delete_content.go).
"""

from .assign import AssignResult, assign
from .delete import delete_file_id, delete_file_ids
from .upload import UploadResult, download, upload_data

__all__ = [
    "AssignResult",
    "assign",
    "UploadResult",
    "upload_data",
    "download",
    "delete_file_id",
    "delete_file_ids",
]
