"""Assign a file id (and target volume server) from the master.

Reference: weed/operation/assign_file_id.go.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..pb import master_pb2
from ..pb import rpc as rpclib
from ..util import failsafe, faultpoint

FP_ASSIGN = faultpoint.register("operation.assign")


@dataclass
class AssignResult:
    fid: str
    url: str
    public_url: str
    count: int
    auth: str = ""

    def fid_url(self) -> str:
        return f"http://{self.url}/{self.fid}"


def assign(
    master_grpc: str,
    count: int = 1,
    collection: str = "",
    replication: str = "",
    ttl: str = "",
    data_center: str = "",
    rack: str = "",
    timeout: float = 30.0,
) -> AssignResult:
    resp = rpclib.master_stub(master_grpc, timeout=timeout).Assign(
        master_pb2.AssignRequest(
            count=count,
            collection=collection,
            replication=replication,
            ttl=ttl,
            data_center=data_center,
            rack=rack,
        )
    )
    if resp.error:
        raise RuntimeError(f"assign: {resp.error}")
    return AssignResult(
        fid=resp.fid,
        url=resp.url,
        public_url=resp.public_url or resp.url,
        count=int(resp.count or count),
        auth=resp.auth,
    )


def assign_any(master_grpcs: list[str], **kwargs) -> AssignResult:
    """Try each master in turn (leader chasing for one-shot callers),
    under the shared failover policy: breaker-gated per master, jittered
    backoff between full rounds.  Assign is idempotent (an orphaned fid
    costs one needle slot, never corrupts data), so everything transient
    retries."""

    def attempt(master: str) -> AssignResult:
        faultpoint.inject(FP_ASSIGN, ctx=master)
        return assign(master, **kwargs)

    try:
        return failsafe.call_with_failover(
            list(master_grpcs), attempt, op="assign",
            retry_type="operation", policy=failsafe.RPC_POLICY,
            idempotent=True,
        )
    except Exception as e:
        raise RuntimeError(f"assign failed on all masters: {e}") from e
