from .mesh import (  # noqa: F401
    batch_encode_sharded,
    distributed_reconstruct,
    make_mesh,
    train_step,
)
