"""Multi-chip EC: sharded batch encode and collective decode over a Mesh.

This is the ICI story for the codec (SURVEY.md §2.9, BASELINE config 4:
batch ec.encode of 64 volumes across a v5e-8 slice):

* ``batch_encode_sharded`` — (V, 10, B) volumes with V sharded over the
  ``dp`` mesh axis and the block/column dimension over ``sp``.  Parity is
  columnwise so encode partitions with ZERO collectives; XLA just runs the
  fused GF kernel per device.

* ``distributed_reconstruct`` — the decode matmul with the *shard* axis
  split across ``dp``.  GF addition is XOR, which integer matmuls can't
  accumulate across devices — but in the bit-plane formulation XOR is
  addition mod 2, so each device computes the partial int32 bit-matmul over
  its local shards, a ``psum`` over ``dp`` rides the ICI, and the mod-2 is
  taken after the collective.  This is the TPU-native analogue of the
  reference's parallel 10-of-14 recovery fan-in (store_ec.go:324-378).

Tested on a virtual 8-device CPU mesh; the same code drives real slices.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import gf256
from ..ops.rs_jax import _multiples, _rows_of, make_apply_xor


def make_mesh(
    devices=None,
    axis_names=("dp", "sp"),
    dp: int | None = None,
    shard_axis: int = 10,
) -> Mesh:
    """2-D mesh: dp (volumes / shard-splitting) x sp (block columns).

    ``dp`` must divide both the device count and the GF shard axis
    (``distributed_reconstruct`` splits S=10 shards over dp).  When not
    given, pick the largest valid dp ≤ sqrt(n) so the mesh stays balanced:
    n=8 -> (2, 4); n=4 -> (2, 2); n=16 -> (2, 8); odd n -> (1, n).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        dp = 1
        for cand in range(2, int(n**0.5) + 1):
            if n % cand == 0 and shard_axis % cand == 0:
                dp = cand
    elif n % dp or shard_axis % dp:
        raise ValueError(
            f"dp={dp} must divide both device count {n} and "
            f"shard axis {shard_axis}"
        )
    sp = n // dp
    arr = np.asarray(devices[: dp * sp]).reshape(dp, sp)
    return Mesh(arr, axis_names)


# ---------------------------------------------------------------------------
# Batch encode: pure data/sequence parallel, no collectives.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _batch_encoder(rows: tuple[tuple[int, ...], ...]):
    apply_one = make_apply_xor(rows)

    def encode(batch: jax.Array) -> jax.Array:  # (V, S, B) -> (V, R, B)
        return jax.vmap(apply_one)(batch)

    return encode


@functools.lru_cache(maxsize=None)
def _sharded_encoder(mesh: Mesh, data_shards: int, parity_shards: int):
    """One jitted sharded encoder per (mesh, geometry) — rebuilding the
    jit wrapper per call would recompile on EVERY invocation, turning a
    multi-step batch encode into a compile storm."""
    rows = _rows_of(gf256.rs_parity_matrix(data_shards, parity_shards))
    encode = _batch_encoder(rows)
    in_sharding = NamedSharding(mesh, P("dp", None, "sp"))
    out_sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.jit(encode, in_shardings=in_sharding,
                   out_shardings=out_sharding)


def batch_encode_sharded(
    mesh: Mesh,
    volumes: jax.Array | np.ndarray,
    data_shards: int = 10,
    parity_shards: int = 4,
) -> jax.Array:
    """Encode (V, data_shards, B) -> (V, parity_shards, B) over the mesh.

    V shards over ``dp``, B over ``sp``; the stripe axis stays local.
    """
    fn = _sharded_encoder(mesh, data_shards, parity_shards)
    return fn(jnp.asarray(volumes))


@functools.lru_cache(maxsize=None)
def _sharded_apply(mesh: Mesh, rows: tuple[tuple[int, ...], ...]):
    """One jitted sharded batch-apply per (mesh, matrix): the codec
    service dispatches encode (parity rows) and decode (plan rows)
    batches through the same entry, so both inherit the dp x sp layout
    without a recompile per batch."""
    apply_one = make_apply_xor(rows)
    sharding = NamedSharding(mesh, P("dp", None, "sp"))
    return jax.jit(jax.vmap(apply_one), in_shardings=sharding,
                   out_shardings=sharding)


def batch_apply_sharded(
    mesh: Mesh,
    matrix: np.ndarray,
    batch: jax.Array | np.ndarray,
) -> jax.Array:
    """Apply one (R, S) GF matrix to (V, S, B) batched inputs over the
    mesh: V shards over ``dp``, B over ``sp``.  The generalisation of
    ``batch_encode_sharded`` to arbitrary matrices (decode plans,
    survivor->wanted rebuild rows); dispatch is async, so the caller can
    keep a second batch in flight while this one computes."""
    return _sharded_apply(mesh, _rows_of(np.asarray(matrix)))(
        jnp.asarray(batch))


# ---------------------------------------------------------------------------
# Distributed decode: shard axis split over dp, psum-mod-2 over ICI.
# ---------------------------------------------------------------------------


def _bit_unpack(data: jax.Array) -> jax.Array:
    """(S, B) uint8 -> (8S, B) int8 bit-planes."""
    s, b = data.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = ((data[:, None, :] >> shifts[None, :, None]) & 1).astype(jnp.int8)
    return bits.reshape(s * 8, b)


def _bit_pack(pbits: jax.Array) -> jax.Array:
    """(8R, B) -> (R, B) uint8."""
    r8, b = pbits.shape
    p = pbits.reshape(r8 // 8, 8, b).astype(jnp.uint8)
    out = p[:, 0, :]
    for k in range(1, 8):
        out = out | (p[:, k, :] << k)
    return out


def distributed_reconstruct(
    mesh: Mesh,
    matrix: np.ndarray,
    inputs: jax.Array | np.ndarray,
) -> jax.Array:
    """Apply a (R, S) GF matrix to (S, B) inputs with S split over ``dp``
    and B over ``sp``; partial bit-matmuls psum over ``dp``.

    S must be divisible by the dp axis size (10 and 2 in practice).
    """
    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # older jax kept it under experimental
        from jax.experimental.shard_map import shard_map

    r, s = matrix.shape
    dp = mesh.shape["dp"]
    if s % dp:
        raise ValueError(f"shard axis {s} not divisible by dp={dp}")
    a = gf256.bit_matrix(np.asarray(matrix, dtype=np.uint8)).astype(np.int8)
    a = a.reshape(8 * r, s, 8).transpose(1, 0, 2)  # (S, 8R, 8) per-shard slices

    def local_fn(a_local: jax.Array, x_local: jax.Array) -> jax.Array:
        # a_local: (S/dp, 8R, 8), x_local: (S/dp, B/sp)
        s_loc = x_local.shape[0]
        bits = _bit_unpack(x_local)  # (8*S/dp, B/sp)
        a_flat = a_local.transpose(1, 0, 2).reshape(8 * r, 8 * s_loc)
        partial = jax.lax.dot_general(
            a_flat, bits, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        total = jax.lax.psum(partial, axis_name="dp")  # ICI collective
        return _bit_pack(total & 1)

    fn = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P("dp", None, None), P("dp", "sp")),
        out_specs=P(None, "sp"),
    )
    return jax.jit(fn)(jnp.asarray(a), jnp.asarray(inputs))


# ---------------------------------------------------------------------------
# The "full training step" analogue: encode a sharded batch of volumes AND
# run a distributed decode — exercises dp, sp shardings and a dp-psum.
# ---------------------------------------------------------------------------


def train_step(
    mesh: Mesh,
    volumes: jax.Array | np.ndarray,
    decode_inputs: jax.Array | np.ndarray,
    decode_matrix: np.ndarray,
) -> tuple[jax.Array, jax.Array]:
    parity = batch_encode_sharded(mesh, volumes)
    rebuilt = distributed_reconstruct(mesh, decode_matrix, decode_inputs)
    return parity, rebuilt
