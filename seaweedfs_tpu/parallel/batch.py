"""File-level batch EC encode: many volumes through one sharded dispatch.

BASELINE config 4 ("batch ec.encode of 64 volumes sharded across v5e-8
over ICI") as a user-facing flow, not just the dryrun: given N volume
base paths, each slice step stacks the v-th stripe slice of every volume
into one (V, 10, W) block, runs the mesh-sharded GF encode (V over
``dp``, columns over ``sp`` — zero collectives, parity is columnwise),
and appends each volume's data+parity to its own `.ec00`–`.ec13` files.

Volumes of different sizes batch together: slices past a volume's end are
zero-padded on the way in and trimmed on the way out, so the shard files
are byte-identical to a per-volume `generate_ec_files` run (pinned in
tests/test_parallel.py).  Stripe geometry is shared with the serial
encoder (`_slice_tasks` + `fill_stripe_rows`), so the two paths cannot
drift.

``slice_size`` is the TOTAL per-shard step budget across all volumes:
the per-volume slice narrows as the batch widens, keeping the host-side
step buffer at ~10*slice_size bytes whether 1 volume or 64 are batched.
Shard writes run on their own thread, overlapping the next step's reads
and device encode (same reasoning as the serial pipeline: on write-bound
disks this is the difference between sum and max of the stages).
"""

from __future__ import annotations

import os
import queue
import threading

import numpy as np

from ..ops import gf256
from ..storage.ec.constants import (
    DATA_SHARDS,
    LARGE_BLOCK_SIZE,
    SMALL_BLOCK_SIZE,
    TOTAL_SHARDS,
    to_ext,
)
from ..storage.ec.encoder import (
    DEFAULT_SLICE,
    _read_at,
    _slice_tasks,
    fill_stripe_rows,
)
from .mesh import batch_encode_sharded, distributed_reconstruct, make_mesh


def batch_generate_ec_files(
    bases: list[str],
    mesh=None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    slice_size: int = DEFAULT_SLICE,
    progress=None,
) -> None:
    """Encode every `<base>.dat` into `<base>.ec00`..`.ec13`, batched.

    `progress(volume_bytes_done_total)` fires after each batched step's
    bytes hit the output files (real bytes only, padding excluded).
    """
    if not bases:
        return

    # total step budget -> per-volume slice, floored to one small block so
    # row batching still engages
    per_vol_slice = max(slice_size // len(bases), small_block_size)

    vols = []
    try:
        for base in bases:
            dat_size = os.path.getsize(base + ".dat")
            vols.append({
                "f": open(base + ".dat", "rb"), "outs": [], "base": base,
                "dat_size": dat_size, "consumed": 0,
                "tasks": list(_slice_tasks(dat_size, large_block_size,
                                           small_block_size,
                                           per_vol_slice))})
        have_work = any(v["tasks"] for v in vols)
        if have_work and mesh is None:
            # the mesh must exist BEFORE the shard files open 'wb': a
            # device-init failure here must not truncate existing shards
            mesh = make_mesh()
        for v in vols:
            for i in range(TOTAL_SHARDS):
                v["outs"].append(open(v["base"] + to_ext(i), "wb"))
        if not have_work:
            return  # all volumes empty: empty shard files, no device touch
        _run_steps(vols, mesh, mesh.shape["dp"], progress)
    finally:
        for v in vols:
            v["f"].close()
            for o in v["outs"]:
                o.close()


def mesh_rebuild_ec_files(
    base_name: str,
    mesh=None,
    slice_size: int = DEFAULT_SLICE,
    progress=None,
) -> list[int]:
    """Regenerate missing `.ecNN` files with the decode matmul sharded over
    the mesh: survivors' shard axis splits over ``dp`` (partial bit-plane
    matmuls psum over the ICI), columns over ``sp``.

    The distributed analogue of storage.ec.encoder.rebuild_ec_files
    (reference envelope: ec_encoder.go:233-287) — same file semantics,
    byte-identical output (pinned in tests/test_parallel.py), but the GF
    work runs as ONE collective program per slice instead of a host loop.
    Missing parity rows are composed into the same survivor->wanted matrix
    (parity = generator-row x decode-matrix over GF), so data and parity
    shards rebuild in a single sharded dispatch.

    `progress(shard_bytes_done)` mirrors the serial rebuild's callback.
    """
    present = [i for i in range(TOTAL_SHARDS)
               if os.path.exists(base_name + to_ext(i))]
    missing = [i for i in range(TOTAL_SHARDS) if i not in present]
    if not missing:
        return []
    if len(present) < DATA_SHARDS:
        raise ValueError(
            f"cannot rebuild: only {len(present)} of {TOTAL_SHARDS} "
            "shards present")
    if mesh is None:
        mesh = make_mesh()
    sp = mesh.shape["sp"]

    sub = present[:DATA_SHARDS]  # survivors actually read, in shard order
    matrix = gf256.rs_matrix(DATA_SHARDS, TOTAL_SHARDS)
    dec = gf256.decode_matrix_for(matrix, DATA_SHARDS, present)
    # survivor -> wanted rows: data rows straight from the decode matrix,
    # parity rows composed through it (GF matrix product)
    rows = np.stack([
        dec[i] if i < DATA_SHARDS
        else gf256.mat_mul(matrix[i:i + 1, :DATA_SHARDS], dec)[0]
        for i in missing
    ]).astype(np.uint8)

    shard_size = os.path.getsize(base_name + to_ext(sub[0]))
    ins = {i: open(base_name + to_ext(i), "rb") for i in sub}
    outs = {i: open(base_name + to_ext(i), "wb") for i in missing}
    try:
        for off in range(0, shard_size, slice_size):
            width = min(slice_size, shard_size - off)
            # columns must split evenly over sp for the shard_map
            w_pad = -(-width // sp) * sp
            inputs = np.zeros((DATA_SHARDS, w_pad), dtype=np.uint8)
            for row, i in enumerate(sub):
                inputs[row, :width] = _read_at(ins[i], off, width)
            rebuilt = np.asarray(
                distributed_reconstruct(mesh, rows, inputs))
            for row, i in enumerate(missing):
                outs[i].write(
                    np.ascontiguousarray(rebuilt[row, :width]))
            if progress is not None:
                progress(off + width)
    finally:
        for h in ins.values():
            h.close()
        for h in outs.values():
            h.close()
    return missing


def _run_steps(vols, mesh, dp: int, progress) -> None:
    # pad the volume axis so it splits evenly over dp (padding volumes are
    # all-zero and never written anywhere)
    v_real = len(vols)
    v_padded = -(-v_real // dp) * dp

    # writer thread: shard appends overlap the next step's reads + encode
    wq: queue.Queue = queue.Queue(maxsize=2)
    write_err: list[Exception] = []
    done = 0

    def writer() -> None:
        nonlocal done
        while True:
            item = wq.get()
            if item is None:
                return
            if write_err:
                continue  # drain so the producer never blocks
            try:
                data, parity, widths = item
                for vi, v in enumerate(vols):
                    w = widths[vi]
                    if w == 0:
                        continue
                    for i in range(DATA_SHARDS):
                        v["outs"][i].write(data[vi, i, :w])
                    for i in range(parity.shape[1]):
                        v["outs"][DATA_SHARDS + i].write(
                            np.ascontiguousarray(parity[vi, i, :w]))
                    real = min(w * DATA_SHARDS,
                               v["dat_size"] - v["consumed"])
                    v["consumed"] += real
                    done += real
                if progress is not None:
                    progress(done)
            except Exception as e:  # surfaced by the main thread
                write_err.append(e)

    wt = threading.Thread(target=writer, name="batch-ec-writer", daemon=True)
    wt.start()
    try:
        steps = max(len(v["tasks"]) for v in vols)
        # one uniform step width -> ONE compiled program for the whole
        # run; narrower tail steps zero-pad in and trim on write
        step_widths = [
            [sum(seg[3] for seg in v["tasks"][step])
             if step < len(v["tasks"]) else 0
             for v in vols]
            for step in range(steps)
        ]
        w_pad = max(max(ws) for ws in step_widths)
        for step in range(steps):
            widths = step_widths[step]
            data = np.zeros((v_padded, DATA_SHARDS, w_pad), dtype=np.uint8)
            for vi, v in enumerate(vols):
                if step < len(v["tasks"]):
                    fill_stripe_rows(v["f"], v["tasks"][step],
                                     data[vi, :, :widths[vi]])
            parity = np.asarray(batch_encode_sharded(mesh, data))
            wq.put((data, parity, widths))
            if write_err:
                raise write_err[0]
        wq.put(None)
        wt.join()
        if write_err:
            raise write_err[0]
    finally:
        if wt.is_alive():
            while True:
                try:
                    wq.get_nowait()
                except queue.Empty:
                    break
            wq.put(None)
            wt.join()
