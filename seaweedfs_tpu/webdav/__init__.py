"""WebDAV gateway over the filer namespace.

Reference: weed/server/webdav_server.go:45 (golang.org/x/net/webdav FS
adapter over filer gRPC), `weed webdav` command.
"""

from .server import WebDavServer

__all__ = ["WebDavServer"]
