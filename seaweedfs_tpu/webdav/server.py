"""WebDAV (class 1+2) server backed by the filer.

Reference: weed/server/webdav_server.go:45,53 — the reference adapts the
filer to golang.org/x/net/webdav's FileSystem interface (whose memLS
provides class-2 locking); here the DAV verbs (OPTIONS/PROPFIND/
PROPPATCH/MKCOL/GET/PUT/DELETE/MOVE/COPY/HEAD/LOCK/UNLOCK) are served
directly over the filer's gRPC metadata + HTTP data planes, with an
in-memory exclusive-write lock table (RFC 4918 §6-9: timeouts, depth-
infinity ancestor coverage, lock-null resource creation, If-header
token checks answering 423 otherwise) — which covers davfs2/cadaver/
Finder AND the Windows/Office write clients that refuse class-1 shares.
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from ..util.httpd import FrameworkHTTPServer

from ..s3api.filer_client import FilerClient
from ..util import glog
from ..util.http_util import read_chunked_body

DAV_NS = "DAV:"


def _entry_size(entry) -> int:
    size = 0
    for c in entry.chunks:
        size = max(size, c.offset + c.size)
    return size or entry.attributes.file_size or len(entry.content)


class WebDavServer:
    def __init__(self, filer: str = "127.0.0.1:8888", port: int = 7333):
        self.port = port
        self.client = FilerClient(filer)
        self._httpd: ThreadingHTTPServer | None = None
        # class-2 lock table: path -> {token, owner, expires, depth}
        # (in-memory, like golang.org/x/net/webdav's memLS the reference
        # serves its locks from)
        self._locks: dict[str, dict] = {}
        self._locks_guard = threading.Lock()

    def acquire_lock(self, path: str, owner: str, timeout_s: float,
                     depth_infinity: bool) -> dict | None:
        """-> lock dict, or None when a live conflicting lock exists."""
        import time as _time
        import uuid

        with self._locks_guard:
            self._expire_locked()
            conflict = self._covering_lock(path)
            if conflict is not None:
                return None
            if depth_infinity:
                # an exclusive subtree lock conflicts with any live lock
                # below it (two "exclusive" locks must never overlap)
                prefix = path.rstrip("/") + "/"
                if any(p.startswith(prefix) for p in self._locks):
                    return None
            lock = {
                "token": f"opaquelocktoken:{uuid.uuid4()}",
                "owner": owner,
                "expires": _time.monotonic() + timeout_s,
                "timeout": timeout_s,
                "depth": "infinity" if depth_infinity else "0",
                "path": path,
            }
            self._locks[path] = lock
            return lock

    def refresh_lock(self, path: str, token: str,
                     timeout_s: float) -> dict | None:
        import time as _time

        with self._locks_guard:
            self._expire_locked()
            lock = self._locks.get(path)
            if lock is None or lock["token"] != token:
                return None
            lock["expires"] = _time.monotonic() + timeout_s
            lock["timeout"] = timeout_s
            return lock

    def release_lock(self, path: str, token: str) -> bool:
        with self._locks_guard:
            lock = self._locks.get(path)
            if lock is None or lock["token"] != token:
                return False
            del self._locks[path]
            return True

    def covering_lock(self, path: str) -> dict | None:
        with self._locks_guard:
            self._expire_locked()
            return self._covering_lock(path)

    def descendant_locks(self, path: str) -> list[dict]:
        """Live locks held BELOW path — a directory delete/move (or a
        depth-infinity lock) conflicts with them (RFC 4918 §6.1/7)."""
        prefix = path.rstrip("/") + "/"
        with self._locks_guard:
            self._expire_locked()
            return [lk for p, lk in self._locks.items()
                    if p.startswith(prefix)]

    def _covering_lock(self, path: str) -> dict | None:
        lock = self._locks.get(path)
        if lock is not None:
            return lock
        # depth-infinity locks on ancestors cover the subtree
        at = path
        while at not in ("", "/"):
            at = at.rsplit("/", 1)[0] or "/"
            lock = self._locks.get(at)
            if lock is not None and lock["depth"] == "infinity":
                return lock
        return None

    def _expire_locked(self) -> None:
        import time as _time

        now = _time.monotonic()
        for p in [p for p, lk in self._locks.items()
                  if lk["expires"] <= now]:
            del self._locks[p]

    def remove_locks_under(self, path: str) -> None:
        """Locks die with the resource (RFC 4918 §9.6): a successful
        DELETE/MOVE drops the lock at path and below, so a stale token
        can't 423-block re-creation for up to the lock timeout."""
        prefix = path.rstrip("/") + "/"
        with self._locks_guard:
            for p in [p for p in self._locks
                      if p == path or p.startswith(prefix)]:
                del self._locks[p]

    def start(self) -> None:
        handler = type("BoundDavHandler", (DavHandler,), {"dav": self})
        self._httpd = FrameworkHTTPServer(("0.0.0.0", self.port), handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        glog.info("webdav started port=%d filer=%s", self.port,
                  self.client.http_address)

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


class DavHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "seaweedfs-tpu-webdav"
    dav: WebDavServer = None  # injected

    def log_message(self, fmt, *args):
        pass

    # -- plumbing ------------------------------------------------------------

    def _path(self) -> str:
        p = urllib.parse.unquote(urllib.parse.urlsplit(self.path).path)
        return "/" + p.strip("/") if p.strip("/") else "/"

    def _send(self, status: int, body: bytes = b"",
              content_type: str = "text/xml; charset=utf-8",
              extra: dict | None = None):
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("DAV", "1,2")
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _find(self, path: str):
        if path == "/":
            from ..pb import filer_pb2

            root = filer_pb2.Entry(name="/", is_directory=True)
            return root
        directory, name = path.rsplit("/", 1)
        return self.dav.client.find_entry(directory or "/", name)

    def _read_body(self) -> bytes:
        if "chunked" in (self.headers.get("Transfer-Encoding") or "").lower():
            # curl -T - and several DAV clients stream uploads chunked;
            # a malformed stream raises and the verb answers 400 rather
            # than storing a truncated body
            return read_chunked_body(self.rfile)
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    # -- verbs ---------------------------------------------------------------

    def do_OPTIONS(self):
        self._send(200, extra={
            "Allow": "OPTIONS, GET, HEAD, PUT, DELETE, PROPFIND, "
                     "PROPPATCH, MKCOL, MOVE, COPY, LOCK, UNLOCK",
            "MS-Author-Via": "DAV",
        })

    # -- class-2 locking (RFC 4918 §9.10/9.11) ----------------------------

    def _refuse_locked(self) -> None:
        """Answer 423 with keep-alive hygiene: the unread request body
        must not be parsed as the next request line (the Windows DAV
        redirector pipelines on one connection) — drained in bounded
        chunks, never buffered."""
        from ..util.httpd import drain_request_body

        drain_request_body(self)
        self._send(423)

    def _may_modify(self, path: str, subtree: bool = False) -> bool:
        """True when no live lock covers path, or the request's If /
        Lock-Token headers carry the covering lock's token.  With
        `subtree` (directory DELETE/MOVE), locks held on DESCENDANTS
        block too — removing a tree must not destroy a locked child."""
        presented = (self.headers.get("If", "") + " "
                     + self.headers.get("Lock-Token", ""))
        lock = self.dav.covering_lock(path)
        if lock is not None and lock["token"] not in presented:
            return False
        if subtree:
            for lk in self.dav.descendant_locks(path):
                if lk["token"] not in presented:
                    return False
        return True

    def _timeout_seconds(self) -> float:
        hdr = self.headers.get("Timeout", "")
        for part in hdr.split(","):
            part = part.strip()
            if part.lower().startswith("second-"):
                try:
                    return min(float(part[len("second-"):]), 3600.0)
                except ValueError:
                    pass
        return 3600.0

    def _lock_xml(self, lock: dict) -> bytes:
        prop = ET.Element(f"{{{DAV_NS}}}prop")
        disc = ET.SubElement(prop, f"{{{DAV_NS}}}lockdiscovery")
        al = ET.SubElement(disc, f"{{{DAV_NS}}}activelock")
        lt = ET.SubElement(al, f"{{{DAV_NS}}}locktype")
        ET.SubElement(lt, f"{{{DAV_NS}}}write")
        ls = ET.SubElement(al, f"{{{DAV_NS}}}lockscope")
        ET.SubElement(ls, f"{{{DAV_NS}}}exclusive")
        ET.SubElement(al, f"{{{DAV_NS}}}depth").text = lock["depth"]
        if lock["owner"]:
            ET.SubElement(al, f"{{{DAV_NS}}}owner").text = lock["owner"]
        ET.SubElement(al, f"{{{DAV_NS}}}timeout").text = (
            f"Second-{int(lock['timeout'])}")
        tok = ET.SubElement(al, f"{{{DAV_NS}}}locktoken")
        ET.SubElement(tok, f"{{{DAV_NS}}}href").text = lock["token"]
        ET.register_namespace("D", DAV_NS)
        return b'<?xml version="1.0" encoding="utf-8"?>' + \
            ET.tostring(prop)

    def do_LOCK(self):
        path = self._path()
        try:
            body = self._read_body()
        except ValueError as e:
            return self._send(400, str(e).encode())
        timeout_s = self._timeout_seconds()
        if not body:
            # refresh: the If header names the token being extended
            presented = self.headers.get("If", "")
            lock = self.dav.covering_lock(path)
            if lock is None or lock["token"] not in presented:
                return self._send(412)
            lock = self.dav.refresh_lock(lock["path"], lock["token"],
                                         timeout_s)
            return self._send(200, self._lock_xml(lock),
                              extra={"Lock-Token": f"<{lock['token']}>"})
        owner = ""
        try:
            root = ET.fromstring(body)
            o = root.find(f"{{{DAV_NS}}}owner")
            if o is not None:
                owner = "".join(o.itertext()).strip()
        except ET.ParseError:
            return self._send(400)
        depth_inf = (self.headers.get("Depth", "infinity").lower()
                     != "0")
        lock = self.dav.acquire_lock(path, owner, timeout_s, depth_inf)
        if lock is None:
            return self._send(423)
        created = False
        if self._find(path) is None:
            # RFC 4918: LOCK on an unmapped URL creates an empty
            # resource (golang webdav's behavior the reference inherits)
            self.dav.client.put_object(path, b"")
            created = True
        self._send(201 if created else 200, self._lock_xml(lock),
                   extra={"Lock-Token": f"<{lock['token']}>"})

    def do_UNLOCK(self):
        path = self._path()
        token = self.headers.get("Lock-Token", "").strip().strip("<>")
        lock = self.dav.covering_lock(path)
        if lock is None or lock["token"] != token:
            return self._send(409)
        self.dav.release_lock(lock["path"], token)
        self._send(204)

    def do_PROPPATCH(self):
        path = self._path()
        if not self._may_modify(path):
            return self._refuse_locked()
        try:
            body = self._read_body()
        except ValueError as e:
            return self._send(400, str(e).encode())
        if self._find(path) is None:
            return self._send(404)
        # acknowledge every requested property (dead-prop storage is not
        # modeled; clients mostly PROPPATCH timestamps after uploads)
        props: list[str] = []
        try:
            root = ET.fromstring(body or b"<propertyupdate/>")
            for prop in root.iter():
                if prop.tag.endswith("}prop"):
                    props.extend(c.tag for c in prop)
        except ET.ParseError:
            return self._send(400)
        ms = ET.Element(f"{{{DAV_NS}}}multistatus")
        resp = ET.SubElement(ms, f"{{{DAV_NS}}}response")
        ET.SubElement(resp, f"{{{DAV_NS}}}href").text = path
        stat = ET.SubElement(resp, f"{{{DAV_NS}}}propstat")
        pr = ET.SubElement(stat, f"{{{DAV_NS}}}prop")
        for tag in props:
            ET.SubElement(pr, tag)
        ET.SubElement(stat, f"{{{DAV_NS}}}status").text = \
            "HTTP/1.1 200 OK"
        ET.register_namespace("D", DAV_NS)
        self._send(207, b'<?xml version="1.0" encoding="utf-8"?>'
                   + ET.tostring(ms))

    def do_PROPFIND(self):
        try:
            self._read_body()  # propfind body ignored: we return allprop
        except ValueError as e:
            return self._send(400, str(e).encode())
        path = self._path()
        entry = self._find(path)
        if entry is None:
            return self._send(404)
        depth = self.headers.get("Depth", "1")
        ET.register_namespace("D", DAV_NS)
        ms = ET.Element(f"{{{DAV_NS}}}multistatus")
        self._propfind_response(ms, path, entry)
        if entry.is_directory and depth != "0":
            listing = self.dav.client.list_entries(
                path if path != "/" else "/", limit=10000
            )
            for e in listing:
                child = f"{path.rstrip('/')}/{e.name}"
                self._propfind_response(ms, child, e)
        body = (b'<?xml version="1.0" encoding="utf-8"?>'
                + ET.tostring(ms))
        self._send(207, body)

    def _propfind_response(self, ms, path: str, entry) -> None:
        resp = ET.SubElement(ms, f"{{{DAV_NS}}}response")
        href = ET.SubElement(resp, f"{{{DAV_NS}}}href")
        href.text = urllib.parse.quote(
            path + ("/" if entry.is_directory and path != "/" else "")
        )
        propstat = ET.SubElement(resp, f"{{{DAV_NS}}}propstat")
        prop = ET.SubElement(propstat, f"{{{DAV_NS}}}prop")
        rtype = ET.SubElement(prop, f"{{{DAV_NS}}}resourcetype")
        if entry.is_directory:
            ET.SubElement(rtype, f"{{{DAV_NS}}}collection")
        else:
            length = ET.SubElement(prop, f"{{{DAV_NS}}}getcontentlength")
            length.text = str(_entry_size(entry))
            ctype = ET.SubElement(prop, f"{{{DAV_NS}}}getcontenttype")
            ctype.text = entry.attributes.mime or "application/octet-stream"
        modified = ET.SubElement(prop, f"{{{DAV_NS}}}getlastmodified")
        modified.text = formatdate(entry.attributes.mtime or 0, usegmt=True)
        status = ET.SubElement(propstat, f"{{{DAV_NS}}}status")
        status.text = "HTTP/1.1 200 OK"

    def do_GET(self):
        path = self._path()
        entry = self._find(path)
        if entry is None:
            return self._send(404)
        if entry.is_directory:
            return self._send(405, b"", extra={"Allow": "PROPFIND"})
        try:
            resp = self.dav.client.open_object(
                path, range_header=self.headers.get("Range", "")
            )
        except urllib.error.HTTPError as e:
            e.read()
            return self._send(e.code)
        with resp:
            body = resp.read()
        extra = {}
        if resp.headers.get("Content-Range"):
            extra["Content-Range"] = resp.headers["Content-Range"]
        self._send(resp.status, body,
                   content_type=entry.attributes.mime
                   or "application/octet-stream",
                   extra=extra)

    def do_HEAD(self):
        path = self._path()
        entry = self._find(path)
        if entry is None:
            return self._send(404)
        self.send_response(200)
        self.send_header("Content-Length", str(_entry_size(entry)))
        self.send_header("Content-Type",
                         entry.attributes.mime or "application/octet-stream")
        self.send_header("Last-Modified",
                         formatdate(entry.attributes.mtime or 0, usegmt=True))
        self.end_headers()

    def do_PUT(self):
        path = self._path()
        if not self._may_modify(path):
            return self._refuse_locked()
        try:
            body = self._read_body()
        except ValueError as e:
            self._send(400, str(e).encode())
            return
        existed = self._find(path) is not None
        self.dav.client.put_object(
            path, body, mime=self.headers.get("Content-Type", "")
        )
        self._send(204 if existed else 201)

    def do_MKCOL(self):
        from ..util.httpd import drain_request_body

        path = self._path()
        # extended-MKCOL bodies must be drained on EVERY early reply,
        # not just the 423 path, or the keep-alive stream desyncs
        drain_request_body(self)
        if not self._may_modify(path):
            return self._send(423)
        if self._find(path) is not None:
            return self._send(405)
        directory, name = path.rsplit("/", 1)
        try:
            self.dav.client.mkdir(directory or "/", name)
        except IOError as e:
            return self._send(409, str(e).encode())
        self._send(201)

    def do_DELETE(self):
        path = self._path()
        if not self._may_modify(path, subtree=True):
            return self._refuse_locked()
        entry = self._find(path)
        if entry is None:
            return self._send(404)
        directory, name = path.rsplit("/", 1)
        err = self.dav.client.delete_entry(
            directory or "/", name, is_delete_data=True,
            is_recursive=entry.is_directory,
        )
        if not err:
            self.dav.remove_locks_under(path)
        self._send(500 if err else 204)

    def _destination(self) -> str | None:
        dst = self.headers.get("Destination", "")
        if not dst:
            return None
        parsed = urllib.parse.urlsplit(dst)
        p = urllib.parse.unquote(parsed.path)
        return "/" + p.strip("/")

    def do_MOVE(self):
        from ..pb import filer_pb2

        src = self._path()
        dst = self._destination()
        if dst is None:
            return self._send(400)
        if not (self._may_modify(src, subtree=True)
                and self._may_modify(dst, subtree=True)):
            return self._refuse_locked()
        if self._find(src) is None:
            return self._send(404)
        overwrote = self._find(dst) is not None
        if overwrote:
            if self.headers.get("Overwrite", "T") == "F":
                return self._send(412)
            d_dir, d_name = dst.rsplit("/", 1)
            self.dav.client.delete_entry(d_dir or "/", d_name,
                                         is_delete_data=True,
                                         is_recursive=True)
        s_dir, s_name = src.rsplit("/", 1)
        d_dir, d_name = dst.rsplit("/", 1)
        self.dav.client.stub().AtomicRenameEntry(
            filer_pb2.AtomicRenameEntryRequest(
                old_directory=s_dir or "/", old_name=s_name,
                new_directory=d_dir or "/", new_name=d_name,
            )
        )
        # locks travel with neither name: the source resource is gone
        # and the destination was overwritten (RFC 4918 §9.9.3)
        self.dav.remove_locks_under(src)
        self.dav.remove_locks_under(dst)
        self._send(204 if overwrote else 201)

    def do_COPY(self):
        src = self._path()
        dst = self._destination()
        if dst is None:
            return self._send(400)
        if not self._may_modify(dst):
            return self._refuse_locked()
        entry = self._find(src)
        if entry is None:
            return self._send(404)
        if entry.is_directory:
            return self._send(501, b"collection COPY unsupported")
        overwrote = self._find(dst) is not None
        if overwrote and self.headers.get("Overwrite", "T") == "F":
            return self._send(412)
        try:
            resp = self.dav.client.open_object(src)
        except urllib.error.HTTPError as e:
            e.read()
            return self._send(e.code)
        with resp:
            data = resp.read()
        self.dav.client.put_object(dst, data, mime=entry.attributes.mime)
        self._send(204 if overwrote else 201)
