"""Operator tools: load benchmark, offline volume fix/export.

Reference surface: weed/command/benchmark.go, fix.go, export.go.
"""
