"""Client utilities: incremental volume backup, upload/download,
filer.cat / filer.copy.

Reference: weed/command/backup.go (incremental volume mirror via the tail
rpcs), upload.go:51, download.go:32, filer_cat.go:54, filer_copy.go:65.

Divergence from the reference's backup: when the remote has compacted
past the local copy (compaction revision ahead, or remote tail shorter
than the local .dat), the local volume is re-fetched from scratch instead
of locally compacting first — simpler, and correct for a mirror whose
authority is always the remote.
"""

from __future__ import annotations

import json
import os
import secrets
import urllib.parse
import urllib.request

from ..pb import master_pb2, volume_server_pb2 as vspb
from ..pb import rpc as rpclib
from ..storage.needle import Needle
from ..storage.super_block import SuperBlock
from ..storage.volume import Volume

from ..util.http_util import grpc_address as _grpc_addr


def _lookup_volume(master_grpc: str, vid: int) -> str:
    """-> the first location's public url for a volume id."""
    resp = rpclib.master_stub(master_grpc, timeout=30).LookupVolume(
        master_pb2.LookupVolumeRequest(volume_or_file_ids=[str(vid)]))
    locs = resp.volume_id_locations
    if not locs or not locs[0].locations:
        raise LookupError(f"volume {vid} has no locations")
    return locs[0].locations[0].url


def backup_volume(master: str, vid: int, directory: str,
                  collection: str = "") -> dict:
    """Incrementally mirror one volume into `directory` (backup.go).

    Returns {"appended": n, "full_resync": bool}.
    """
    master_grpc = _grpc_addr(master)
    vs_url = _lookup_volume(master_grpc, vid)
    vs_grpc = _grpc_addr(vs_url)
    stub = rpclib.volume_server_stub(vs_grpc, timeout=600)
    stats = stub.VolumeSyncStatus(
        vspb.VolumeSyncStatusRequest(volume_id=vid))

    os.makedirs(directory, exist_ok=True)
    base = os.path.join(
        directory, f"{collection}_{vid}" if collection else str(vid))
    full_resync = False
    if os.path.exists(base + ".dat"):
        vol = Volume(directory, collection, vid)
        local_rev = vol.super_block.compaction_revision
        local_size = vol.content_size
        if local_rev != stats.compact_revision or \
                local_size > stats.tail_offset:
            # the remote compacted (or shrank): this mirror's bytes are
            # no longer a prefix of the remote — start over
            vol.close()
            for ext in (".dat", ".idx"):
                if os.path.exists(base + ext):
                    os.remove(base + ext)
            full_resync = True
            vol = None
        else:
            vol.flush()
    else:
        vol = None
    if vol is None:
        sb = SuperBlock(compaction_revision=stats.compact_revision)
        vol = Volume(directory, collection, vid, super_block=sb)

    since_ns = _last_append_ns(vol)
    appended = 0
    stream = stub.VolumeTailSender(vspb.VolumeTailSenderRequest(
        volume_id=vid, since_ns=since_ns, idle_timeout_seconds=1))
    for resp in stream:
        if resp.is_last_chunk:
            break
        if not resp.needle_header:
            continue
        n = Needle.parse_header(bytes(resp.needle_header))
        full = Needle.from_bytes(
            bytes(resp.needle_header) + bytes(resp.needle_body),
            vol.version, verify=False)
        if n.size > 0:
            vol.append_needle(full)
        else:
            vol.delete_needle(n.id, at_ns=full.append_at_ns)
        appended += 1
    vol.close()
    return {"appended": appended, "full_resync": full_resync}


def _last_append_ns(vol: Volume) -> int:
    from .offline import tail_watermark_ns

    vol.flush()
    return tail_watermark_ns(vol.file_name() + ".dat")


# -- one-shot upload / download ---------------------------------------------


def upload_files(master: str, paths: list[str], collection: str = "",
                 replication: str = "", ttl: str = "") -> list[dict]:
    """`weed upload` (upload.go:51): assign a fid per file, POST the
    bytes to the assigned volume server, report fid+url per file."""
    results = []
    for path in paths:
        with open(path, "rb") as f:
            data = f.read()
        qs = urllib.parse.urlencode({
            "collection": collection, "replication": replication,
            "ttl": ttl})
        with urllib.request.urlopen(
                f"http://{master}/dir/assign?{qs}", timeout=30) as r:
            a = json.loads(r.read())
        if "error" in a and a["error"]:
            raise RuntimeError(a["error"])
        name = os.path.basename(path)
        # random boundary: fixed tokens can collide with binary payloads
        boundary = "----swfs" + secrets.token_hex(16)
        safe_name = name.replace('"', "%22").replace("\r", "").replace("\n", "")
        body = (
            f"--{boundary}\r\nContent-Disposition: form-data; "
            f'name="file"; filename="{safe_name}"\r\n'
            f"Content-Type: application/octet-stream\r\n\r\n"
        ).encode() + data + f"\r\n--{boundary}--\r\n".encode()
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}", data=body, method="POST",
            headers={"Content-Type":
                     f"multipart/form-data; boundary={boundary}",
                     **({"Authorization": f"BEARER {a['auth']}"}
                        if a.get("auth") else {})})
        with urllib.request.urlopen(req, timeout=120) as r:
            up = json.loads(r.read() or b"{}")
        results.append({"fileName": name, "fid": a["fid"],
                        "url": f"{a['url']}/{a['fid']}",
                        "size": up.get("size", len(data))})
    return results


def download_files(master: str, fids: list[str], directory: str = ".") -> list[str]:
    """`weed download` (download.go:32): resolve each fid via the master
    and save the blob under its stored filename (fallback: the fid)."""
    out = []
    for fid in fids:
        vid = fid.partition(",")[0]
        with urllib.request.urlopen(
                f"http://{master}/dir/lookup?volumeId={vid}",
                timeout=30) as r:
            locations = json.loads(r.read())["locations"]
        url = locations[0]["url"]
        req = urllib.request.Request(f"http://{url}/{fid}")
        with urllib.request.urlopen(req, timeout=120) as r:
            data = r.read()
            cd = r.headers.get("Content-Disposition", "")
        name = fid.replace(",", "_")
        if "filename=" in cd:
            # basename() — a hostile server must not steer the write
            # outside the target directory via ../ or an absolute path
            name = os.path.basename(
                cd.split("filename=")[-1].strip('" ')) or name
        path = os.path.join(directory, name)
        with open(path, "wb") as f:
            f.write(data)
        out.append(path)
    return out


# -- filer.cat / filer.copy ---------------------------------------------------


def filer_cat(filer: str, path: str) -> bytes:
    """filer_cat.go:54 — read one filer file's bytes."""
    from ..s3api.filer_client import FilerClient

    status, _, body = FilerClient(filer).get_object(path)
    if status != 200:
        raise FileNotFoundError(f"{path}: HTTP {status}")
    return body


def filer_copy(filer: str, sources: list[str], dest_dir: str) -> list[str]:
    """filer_copy.go:65 — copy local files/directories into the filer
    namespace under dest_dir; returns the created filer paths."""
    from ..s3api.filer_client import FilerClient

    client = FilerClient(filer)
    created = []

    def put_file(local: str, remote: str) -> None:
        size = os.path.getsize(local)
        with open(local, "rb") as f:
            client.put_object_stream(remote, f, size)
        created.append(remote)

    dest_dir = "/" + dest_dir.strip("/")
    for src in sources:
        if os.path.isdir(src):
            root_name = os.path.basename(os.path.normpath(src))
            for dirpath, _dirs, files in os.walk(src):
                rel = os.path.relpath(dirpath, src)
                for fn in files:
                    remote = "/".join(
                        p for p in (dest_dir, root_name,
                                    "" if rel == "." else rel, fn) if p)
                    put_file(os.path.join(dirpath, fn), remote)
        else:
            put_file(src, f"{dest_dir}/{os.path.basename(src)}")
    return created
