"""Cluster load benchmark: concurrent write then read phases with latency
percentiles.

Reference: weed/command/benchmark.go:26-45 (defaults c=16, 1KB files) and
its stats harness (:155-284) — requests/sec, throughput, latency
distribution, and a per-second progress line.
"""

from __future__ import annotations

import random
import threading
import time
import urllib.request
from dataclasses import dataclass, field

from ..operation.assign import assign
from ..operation.upload import upload_data


@dataclass
class Stats:
    name: str
    latencies_ms: list = field(default_factory=list)
    bytes_total: int = 0
    failed: int = 0
    start: float = 0.0
    end: float = 0.0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def record(self, dt_s: float, nbytes: int) -> None:
        with self._lock:
            self.latencies_ms.append(dt_s * 1000)
            self.bytes_total += nbytes

    def fail(self) -> None:
        with self._lock:
            self.failed += 1

    def report(self) -> str:
        lat = sorted(self.latencies_ms)
        n = len(lat)
        took = max(self.end - self.start, 1e-9)
        lines = [
            f"\n------------ {self.name} ----------",
            f"Completed requests:      {n}",
            f"Failed requests:         {self.failed}",
            f"Time taken:              {took:.3f} seconds",
            f"Requests per second:     {n / took:.2f}",
            f"Transfer rate:           {self.bytes_total / 1024 / took:.2f} KB/s",
        ]
        if n:
            avg = sum(lat) / n
            std = (sum((x - avg) ** 2 for x in lat) / n) ** 0.5
            lines += [
                f"Avg latency:             {avg:.2f} ms (std {std:.2f})",
                "Percentage of requests served within a time (ms):",
            ]
            for p in (50, 66, 75, 80, 90, 95, 98, 99, 100):
                i = min(n - 1, int(n * p / 100))
                lines.append(f"   {p:>3}%  {lat[i]:8.2f} ms")
        return "\n".join(lines)


def run_benchmark(
    master: str,
    num_files: int = 1024,
    file_size: int = 1024,
    concurrency: int = 16,
    do_read: bool = True,
    collection: str = "",
    replication: str = "",
) -> dict:
    """Run write (and optionally read) phases; prints the stats blocks and
    returns {'write': Stats, 'read': Stats|None}."""
    master_grpc = _grpc_addr(master)
    rng = random.Random(0)
    payload_base = bytes(rng.randrange(256) for _ in range(file_size))
    fids: list[str] = []
    fid_lock = threading.Lock()
    counter = iter(range(num_files))
    counter_lock = threading.Lock()

    write_stats = Stats("Write Benchmark")

    def write_worker():
        while True:
            with counter_lock:
                try:
                    i = next(counter)
                except StopIteration:
                    return
            try:
                t0 = time.perf_counter()
                a = assign(master_grpc, collection=collection,
                           replication=replication)
                payload = payload_base[:-4] + i.to_bytes(4, "big")
                upload_data(a.fid_url(), payload, filename=f"bench{i}.bin",
                            jwt=a.auth)
                write_stats.record(time.perf_counter() - t0, file_size)
                with fid_lock:
                    fids.append(a.fid)
            except Exception:
                write_stats.fail()

    write_stats.start = time.time()
    threads = [threading.Thread(target=write_worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    write_stats.end = time.time()
    print(write_stats.report())

    read_stats = None
    if do_read and fids:
        read_stats = Stats("Read Benchmark")
        read_counter = iter(range(len(fids)))

        def read_worker():
            while True:
                with counter_lock:
                    try:
                        i = next(read_counter)
                    except StopIteration:
                        return
                fid = fids[i]
                try:
                    t0 = time.perf_counter()
                    vid = fid.split(",", 1)[0]
                    with urllib.request.urlopen(
                        f"http://{master}/dir/lookup?volumeId={vid}", timeout=10
                    ) as r:
                        import json

                        loc = json.loads(r.read())["locations"][0]["publicUrl"]
                    with urllib.request.urlopen(
                        f"http://{loc}/{fid}", timeout=10
                    ) as r:
                        got = r.read()
                    read_stats.record(time.perf_counter() - t0, len(got))
                except Exception:
                    read_stats.fail()

        read_stats.start = time.time()
        threads = [threading.Thread(target=read_worker) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        read_stats.end = time.time()
        print(read_stats.report())

    return {"write": write_stats, "read": read_stats}


def _grpc_addr(master: str) -> str:
    host, port = master.rsplit(":", 1)
    return f"{host}:{int(port) + 10000}"
