"""Offline volume tools: operate on `.dat`/`.idx` without a server.

Reference: `weed fix` rebuilds a corrupted `.idx` by scanning the `.dat`
(weed/command/fix.go:22) and `weed export` writes needles to a tar with
filters (weed/command/export.go:41).
"""

from __future__ import annotations

import io
import os
import tarfile
import time
from typing import Iterator

from ..storage import types as t
from ..storage.idx import IndexWriter, walk_index_file
from ..storage.needle import FLAG_HAS_NAME, Needle, body_length
from ..storage.super_block import SuperBlock


def volume_base(directory: str, volume_id: int, collection: str = "") -> str:
    name = f"{collection}_{volume_id}" if collection else str(volume_id)
    return os.path.join(directory, name)


def scan_dat_file(dat_path: str) -> Iterator[tuple[int, Needle]]:
    """Yield (offset, needle) for every record in a .dat, in file order.

    The reference's ScanVolumeFile walk (needle_read_write.go ReadNeedleHeader
    + body).  Tombstone records (size<0) are yielded too — callers decide.
    """
    import struct

    with open(dat_path, "rb") as f:
        sb = SuperBlock.from_bytes(f.read(64))
        version = sb.version
        offset = sb.block_size()
        f.seek(offset)
        while True:
            header = f.read(t.NEEDLE_HEADER_SIZE)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                return
            n = Needle.parse_header(header)
            size = n.size if n.size > 0 else 0
            body = f.read(body_length(size, version))
            if size > 0:
                n = Needle.from_bytes(header + body, version, verify=False)
            elif version == 3 and len(body) >= 12:
                # tombstone: checksum(4) + append_at_ns(8); consumers like
                # incremental tail sync need deletion timestamps too
                n.append_at_ns = struct.unpack(">Q", body[4:12])[0]
            yield offset, n
            offset += t.NEEDLE_HEADER_SIZE + len(body)


def fix_index(directory: str, volume_id: int, collection: str = "") -> int:
    """Rebuild the .idx by scanning the .dat (weed/command/fix.go:22).
    Returns the number of live entries written."""
    base = volume_base(directory, volume_id, collection)
    dat, idx = base + ".dat", base + ".idx"
    if not os.path.exists(dat):
        raise FileNotFoundError(dat)
    entries: dict[int, tuple[int, int]] = {}
    for offset, n in scan_dat_file(dat):
        if n.size > 0:
            entries[n.id] = (offset, n.size)
        else:
            entries.pop(n.id, None)
    tmp = idx + ".tmp"
    if os.path.exists(tmp):
        os.remove(tmp)
    w = IndexWriter(tmp)
    for key in entries:
        offset, size = entries[key]
        w.put(key, offset, size)
    w.flush()
    w.close()
    os.replace(tmp, idx)
    return len(entries)


def export_volume(directory: str, volume_id: int, collection: str = "",
                  output: str = "export.tar",
                  newer_than_ns: int = 0) -> int:
    """Write live needles to a tar (weed/command/export.go:41).  Entry names
    use the needle name when present, else the hex file id."""
    base = volume_base(directory, volume_id, collection)
    dat = base + ".dat"
    if not os.path.exists(dat):
        raise FileNotFoundError(dat)
    live: dict[int, int] = {}
    idx = base + ".idx"
    if os.path.exists(idx):
        for key, offset, size in walk_index_file(idx):
            if offset > 0 and not t.size_is_deleted(size):
                live[key] = offset
            else:
                live.pop(key, None)
    count = 0
    with tarfile.open(output, "w") as tar:
        for offset, n in scan_dat_file(dat):
            if n.size <= 0:
                continue
            if live and live.get(n.id) != offset:
                continue  # deleted or superseded
            if newer_than_ns and n.append_at_ns and n.append_at_ns < newer_than_ns:
                continue
            if n.has(FLAG_HAS_NAME) and n.name:
                name = n.name.decode(errors="replace")
            else:
                name = f"{volume_id}#{n.id:x}"
            info = tarfile.TarInfo(name=name)
            info.size = len(n.data)
            info.mtime = (n.append_at_ns // 1_000_000_000) or int(time.time())
            tar.addfile(info, io.BytesIO(bytes(n.data)))
            count += 1
    return count


def tail_watermark_ns(dat_path: str) -> int:
    """Max append_at_ns across a .dat (incl. tombstones) — the since_ns
    resume point for tail subscriptions and incremental backup."""
    import os as _os

    last = 0
    if _os.path.exists(dat_path):
        for _off, n in scan_dat_file(dat_path):
            last = max(last, n.append_at_ns)
    return last
