"""S3-compatible gateway over the filer plane.

Reference: weed/s3api/ (s3api_server.go:44 router, auth_signature_v4.go,
filer_multipart.go).  Buckets are directories under /buckets/<name>;
objects are filer entries; multipart uploads splice chunk lists without
copying data.
"""

from .server import S3ApiServer

__all__ = ["S3ApiServer"]
