"""Gateway-side filer access: gRPC for metadata, filer HTTP for bytes.

Reference shape: weed/s3api/s3api_handlers.go (WithFilerClient) +
s3api_object_handlers.go putToFiler/proxy-to-filer — the s3 process keeps
no object state of its own; everything lives in the filer.
"""

from __future__ import annotations

import time
import urllib.error
import urllib.parse

import grpc

from ..filer.fleet.tenant import QuotaExceededError, SlowDownError
from ..pb import filer_pb2
from ..pb import rpc as rpclib
from ..util import connpool, failsafe
from ..util.http_util import trace_headers

GRPC_PORT_OFFSET = 10000


def _raise_if_rejected(e: urllib.error.HTTPError) -> None:
    """Translate a filer-side admission/quota rejection (marked with the
    X-Seaweed-Reject header) into its typed exception — BEFORE the retry
    machinery sees the 503, so a SlowDown is surfaced to the client
    instead of hammered three more times."""
    kind = (e.headers.get("X-Seaweed-Reject", "") if e.headers else "")
    if not kind:
        return
    e.read()
    if kind == "slowdown":
        try:
            retry_after = int(e.headers.get("Retry-After", "1") or 1)
        except ValueError:
            retry_after = 1
        raise SlowDownError("", retry_after=retry_after)
    if kind == "quota":
        raise QuotaExceededError("", "filer shard rejected the write")

# the gateway's edge to the filer: bounded retries, no breaker bypass —
# the filer is the gateway's only backend, so we keep probing it
_S3_POLICY = failsafe.RetryPolicy(max_attempts=3, base_delay=0.05,
                                  max_delay=1.0)


class FilerUnavailable(IOError):
    """The filer could not be reached / errored — NOT a missing entry.

    Callers must surface this as a 5xx, never as NoSuchKey: a sync client
    that sees 404 for an outage will happily delete its local copies."""


class FilerClient:
    def __init__(self, filer_http_address: str):
        self.http_address = filer_http_address
        host, _, port = filer_http_address.partition(":")
        self.grpc_address = f"{host}:{int(port) + GRPC_PORT_OFFSET}"

    def stub(self, timeout: float = 30.0) -> rpclib.Stub:
        return rpclib.filer_stub(self.grpc_address, timeout=timeout)

    # -- metadata ------------------------------------------------------------

    def find_entry(self, directory: str, name: str) -> filer_pb2.Entry | None:
        try:
            resp = failsafe.call(
                lambda: self.stub().LookupDirectoryEntry(
                    filer_pb2.LookupDirectoryEntryRequest(
                        directory=directory, name=name
                    )
                ),
                op="lookup_entry", retry_type="s3", policy=_S3_POLICY,
                idempotent=True,
            )
            return resp.entry
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise FilerUnavailable(f"filer lookup failed: {e.code()}")

    def list_entries(
        self,
        directory: str,
        prefix: str = "",
        start_from: str = "",
        inclusive: bool = False,
        limit: int = 1024,
    ) -> list[filer_pb2.Entry]:
        try:
            return failsafe.call(
                lambda: [
                    r.entry
                    for r in self.stub(timeout=60).ListEntries(
                        filer_pb2.ListEntriesRequest(
                            directory=directory,
                            prefix=prefix,
                            start_from_file_name=start_from,
                            inclusive_start_from=inclusive,
                            limit=limit,
                        )
                    )
                ],
                op="list_entries", retry_type="s3", policy=_S3_POLICY,
                idempotent=True,
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return []
            raise FilerUnavailable(f"filer list failed: {e.code()}")

    def iter_entries(self, directory: str, prefix: str = "",
                     page: int = 1024):
        """Yield every entry of one directory, paging through ListEntries."""
        start, inclusive = "", False
        while True:
            batch = self.list_entries(directory, prefix=prefix,
                                      start_from=start, inclusive=inclusive,
                                      limit=page)
            yield from batch
            if len(batch) < page:
                return
            start, inclusive = batch[-1].name, False

    def walk(self, directory: str):
        """Yield (directory, entry) for the whole subtree, breadth-first."""
        from collections import deque

        queue = deque([directory.rstrip("/") or "/"])
        while queue:
            d = queue.popleft()
            for entry in self.iter_entries(d):
                yield d, entry
                if entry.is_directory:
                    queue.append((d.rstrip("/") or "") + "/" + entry.name)

    def create_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        resp = self.stub().CreateEntry(
            filer_pb2.CreateEntryRequest(directory=directory, entry=entry)
        )
        if resp.error:
            raise IOError(resp.error)

    def update_entry(self, directory: str, entry: filer_pb2.Entry) -> None:
        self.stub().UpdateEntry(
            filer_pb2.UpdateEntryRequest(directory=directory, entry=entry)
        )

    def mkdir(self, directory: str, name: str, mode: int = 0o777) -> None:
        entry = filer_pb2.Entry(name=name, is_directory=True)
        entry.attributes.file_mode = mode | 0o40000
        entry.attributes.mtime = int(time.time())
        entry.attributes.crtime = int(time.time())
        self.create_entry(directory, entry)

    def delete_entry(
        self,
        directory: str,
        name: str,
        is_delete_data: bool = True,
        is_recursive: bool = False,
    ) -> str:
        try:
            resp = self.stub(timeout=60).DeleteEntry(
                filer_pb2.DeleteEntryRequest(
                    directory=directory,
                    name=name,
                    is_delete_data=is_delete_data,
                    is_recursive=is_recursive,
                    ignore_recursive_error=True,
                )
            )
            return resp.error
        except Exception as e:
            return str(e)

    # -- bytes ---------------------------------------------------------------

    def put_object(self, path: str, data: bytes, mime: str = "") -> None:
        # a filer PUT replaces the whole entry, so re-sending after an
        # ambiguous failure converges on the same result: idempotent
        def attempt() -> None:
            try:
                with connpool.request(
                        "PUT",
                        f"http://{self.http_address}"
                        f"{urllib.parse.quote(path)}",
                        body=data,
                        headers=trace_headers(
                            {"Content-Type":
                             mime or "application/octet-stream"}),
                        timeout=failsafe.attempt_timeout(120)) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                _raise_if_rejected(e)
                raise

        failsafe.call(attempt, op="put_object", retry_type="s3",
                      policy=_S3_POLICY, idempotent=True)

    def put_object_stream(self, path: str, reader, length: int,
                          mime: str = "") -> None:
        """PUT from a file-like reader without buffering the whole body
        (http.client streams objects that expose .read).  The pool sends
        a non-seekable stream on a fresh dial — a half-consumed reader
        can't be replayed onto a stale keep-alive socket."""
        try:
            with connpool.request(
                    "PUT",
                    f"http://{self.http_address}{urllib.parse.quote(path)}",
                    body=reader,
                    headers=trace_headers({
                        "Content-Type": mime or "application/octet-stream",
                        "Content-Length": str(length),
                    }),
                    timeout=600) as r:
                r.read()
        except urllib.error.HTTPError as e:
            _raise_if_rejected(e)
            raise

    def open_object(self, path: str, range_header: str = ""):
        """Streaming GET: returns the live HTTP response (file-like with
        .status/.headers) — caller must close it.  Raises HTTPError on
        non-2xx so callers branch on .code."""
        headers = trace_headers()
        if range_header:
            headers["Range"] = range_header
        try:
            return connpool.request(
                "GET",
                f"http://{self.http_address}{urllib.parse.quote(path)}",
                headers=headers, timeout=600)
        except urllib.error.HTTPError as e:
            _raise_if_rejected(e)
            raise

    def get_object(self, path: str, range_header: str = "") -> tuple[int, dict, bytes]:
        """-> (status, headers, body); raises on network failure only."""
        headers = trace_headers()
        if range_header:
            headers["Range"] = range_header
        def attempt() -> tuple[int, dict, bytes]:
            try:
                with connpool.request(
                        "GET",
                        f"http://{self.http_address}"
                        f"{urllib.parse.quote(path)}",
                        headers=headers,
                        timeout=failsafe.attempt_timeout(120)) as r:
                    return r.status, dict(r.headers), r.read()
            except urllib.error.HTTPError as e:
                _raise_if_rejected(e)
                raise

        try:
            return failsafe.call(attempt, op="get_object", retry_type="s3",
                                 policy=_S3_POLICY, idempotent=True)
        except urllib.error.HTTPError as e:
            # non-2xx (after any 5xx retries): surface to the S3 layer
            return e.code, dict(e.headers), e.read()
